"""Worker for the multi-host ensemble test: 4 jax.distributed CPU processes,
2 branches of 2 hosts each (reference: one DDP model per comm.Split
subcommunicator, examples/multidataset/train.py:205-247).

Each branch trains the same architecture on ITS OWN corpus over a HostGroup
mesh.  Asserted by the parent test: params bitwise-identical WITHIN a branch
(in-group gradient sync), different ACROSS branches (no cross-group mixing),
and group-reduced metrics agree within the branch.

Usage: mp_ensemble_worker.py <rank> <world> <port> <scratch>
"""

import hashlib
import json
import os
import sys

rank, world, port = int(sys.argv[1]), int(sys.argv[2]), int(sys.argv[3])
scratch = sys.argv[4]

os.environ["JAX_PLATFORMS"] = "cpu"
os.environ.pop("XLA_FLAGS", None)  # one device per process

import jax

jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}",
    num_processes=world,
    process_id=rank,
)
assert jax.process_count() == world

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
os.chdir(scratch)

import numpy as np

from hydragnn_tpu.config.config import (
    DatasetStats,
    finalize,
    head_specs_from_config,
    label_slices_from_config,
)
from hydragnn_tpu.data.dataloader import create_dataloaders
from hydragnn_tpu.graph.batch import GraphSample
from hydragnn_tpu.graph.neighborlist import radius_graph
from hydragnn_tpu.models.base import ModelConfig
from hydragnn_tpu.models.create import create_model
from hydragnn_tpu.parallel.comm import HostGroup, assign_ensemble_groups
from hydragnn_tpu.train.optimizer import select_optimizer
from hydragnn_tpu.train.trainer import create_train_state, train_validate_test


def make_corpus(color: int, n: int = 96):
    """Branch-specific synthetic corpus: target scale differs per branch so
    the two branches provably learn different models."""
    rng = np.random.RandomState(100 + color)
    samples = []
    for _ in range(n):
        sz = rng.randint(6, 12)
        pos = rng.rand(sz, 3).astype(np.float32) * 2.0
        ei = radius_graph(pos, 1.2, 16)
        if ei.shape[1] == 0:
            continue
        x = rng.rand(sz, 1).astype(np.float32)
        y = (1.0 + color) * x.mean()  # branch-dependent target map
        samples.append(GraphSample(
            x=x, pos=pos, edge_index=ei,
            graph_y=np.asarray([y], np.float32)))
    return samples


color = assign_ensemble_groups([1.0, 1.0])
group = HostGroup(color)
assert group.size == world // 2, (color, group.members)

samples = make_corpus(color)

config = {
    "Dataset": {
        "name": f"branch{color}",
        "graph_features": {"name": ["y"], "dim": [1]},
        "node_features": {"name": ["x"], "dim": [1]},
    },
    "NeuralNetwork": {
        "Architecture": {
            "model_type": "SAGE",
            "radius": 1.2,
            "max_neighbours": 16,
            "hidden_dim": 8,
            "num_conv_layers": 2,
            "output_heads": {
                "graph": {"num_sharedlayers": 1, "dim_sharedlayers": 8,
                          "num_headlayers": 1, "dim_headlayers": [8]}
            },
            "task_weights": [1.0],
        },
        "Variables_of_interest": {
            "input_node_features": [0],
            "output_names": ["y"],
            "output_index": [0],
            "output_dim": [1],
            "type": ["graph"],
        },
        "Training": {
            "num_epoch": 6,
            "perc_train": 0.75,
            "loss_function_type": "mse",
            "batch_size": 8,
            "Optimizer": {"type": "AdamW", "learning_rate": 0.01},
        },
    },
}

n_tr = int(len(samples) * 0.75)
trainset, valset = samples[:n_tr], samples[n_tr:]
stats = DatasetStats.from_samples(samples, need_deg=False)
config = finalize(config, stats)
cfg = ModelConfig.from_config(config["NeuralNetwork"])
model = create_model(cfg)
hs = head_specs_from_config(config)
gs, ns = label_slices_from_config(config)

# members shard the branch corpus between them
tl, vl, sl = create_dataloaders(
    trainset, valset, valset, 8, hs,
    graph_feature_slices=gs, node_feature_slices=ns,
    rank=group.rank, world_size=group.size)

opt = select_optimizer(config["NeuralNetwork"]["Training"]["Optimizer"])
state = create_train_state(model, next(iter(tl)), opt, seed=0)
state, hist = train_validate_test(
    model, cfg, state, opt, tl, vl, sl,
    config["NeuralNetwork"], f"ens{color}", verbosity=0,
    mesh=group.mesh(), logs_dir=os.path.join(scratch, "logs"))

# digest of trained params: must match within the branch, differ across
flat = np.concatenate([
    np.asarray(jax.device_get(x)).ravel()
    for x in jax.tree.leaves(state.params)])
digest = hashlib.sha1(flat.astype(np.float64).tobytes()).hexdigest()[:16]
val = group.mean_scalar(hist["val"][-1])
print(f"ENSRESULT rank={rank} color={color} val={val:.8f} "
      f"params={digest} train_last={hist['train'][-1]:.8f}", flush=True)
