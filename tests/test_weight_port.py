"""Forward parity for the torch->flax weight converter (tools/port_weights).

Strategy: PyG is not installed here, so each supported arch gets a plain-
torch twin whose state_dict keys match the reference checkpoint layout
exactly (``graph_convs.{i}.module_0.*`` PyGSeq nesting included, reference
hydragnn/utils/model.py:58-103 checkpoint format, Base.py:200-279 head
naming) and whose math mirrors the documented conv semantics.  A random
twin checkpoint ported through ``port_state_dict`` must reproduce the flax
model's predictions to 1e-4 — this validates every row of docs/WEIGHTS.md
(transposes, bias placement, Sequential slot arithmetic, BN stats split)
end to end.
"""

import math

import numpy as np
import pytest
import torch
import torch.nn as tnn

from hydragnn_tpu.graph.batch import GraphSample, HeadSpec, PadSpec, collate
from hydragnn_tpu.graph.neighborlist import radius_graph
from hydragnn_tpu.models.base import (
    GraphHeadCfg,
    ModelConfig,
    NodeHeadCfg,
)
from hydragnn_tpu.models.create import create_model, init_model
from tools.port_weights import port_checkpoint, port_state_dict

HIDDEN = 8
IN_DIM = 3
N_NODES = 5
N_GRAPHS = 3
AVG_DEG_LOG = 1.3
AVG_DEG_LIN = 3.5


# ---------------------------------------------------------------------------
# plain-torch twins (reference-keyed state dicts, documented math)
# ---------------------------------------------------------------------------


class TwinSAGE(tnn.Module):
    def __init__(self, din, dout):
        super().__init__()
        self.lin_l = tnn.Linear(din, dout)           # aggregated neighbors
        self.lin_r = tnn.Linear(din, dout, bias=False)  # root

    def forward(self, x, ei, pos):
        src, dst = ei
        deg = torch.bincount(dst, minlength=x.shape[0]).clamp(min=1)
        agg = torch.zeros(x.shape[0], x.shape[1]).index_add_(0, dst, x[src])
        agg = agg / deg[:, None]
        return self.lin_l(agg) + self.lin_r(x)


class TwinGIN(tnn.Module):
    def __init__(self, din, dout):
        super().__init__()
        self.nn = tnn.Sequential(
            tnn.Linear(din, dout), tnn.ReLU(), tnn.Linear(dout, dout))
        self.eps = tnn.Parameter(torch.tensor(100.0))

    def forward(self, x, ei, pos):
        src, dst = ei
        agg = torch.zeros_like(x).index_add_(0, dst, x[src])
        return self.nn((1.0 + self.eps) * x + agg)


class TwinSchNet(tnn.Module):
    def __init__(self, din, dout, num_gaussians=6, num_filters=HIDDEN,
                 cutoff=3.0):
        super().__init__()
        self.nn = tnn.Sequential(
            tnn.Linear(num_gaussians, num_filters), tnn.Identity(),
            tnn.Linear(num_filters, num_filters))
        self.lin1 = tnn.Linear(din, num_filters, bias=False)
        self.lin2 = tnn.Linear(num_filters, dout)
        self.num_gaussians, self.cutoff = num_gaussians, cutoff

    def forward(self, x, ei, pos):
        src, dst = ei
        d = pos[src] - pos[dst]
        w = torch.sqrt((d * d).sum(-1) + 1e-12)
        off = torch.linspace(0.0, self.cutoff, self.num_gaussians)
        coeff = -0.5 / float(off[1] - off[0]) ** 2
        rbf = torch.exp(coeff * (w[:, None] - off[None, :]) ** 2)
        cut = 0.5 * (torch.cos(w * math.pi / self.cutoff) + 1.0)
        cut = torch.where(w <= self.cutoff, cut, torch.zeros_like(cut))
        filt = self.nn[2](_ssp(self.nn[0](rbf))) * cut[:, None]
        h = self.lin1(x)
        msg = h[src] * filt
        agg = torch.zeros(x.shape[0], h.shape[1]).index_add_(0, dst, msg)
        return self.lin2(agg)


def _ssp(x):
    return torch.nn.functional.softplus(x) - math.log(2.0)


class TwinPNA(tnn.Module):
    def __init__(self, din, dout):
        super().__init__()
        self.pre_nns = tnn.ModuleList([tnn.Sequential(tnn.Linear(2 * din, din))])
        self.post_nns = tnn.ModuleList(
            [tnn.Sequential(tnn.Linear(din + 16 * din, dout))])
        self.lin = tnn.Linear(dout, dout)

    def forward(self, x, ei, pos):
        src, dst = ei
        n, f = x.shape
        z = torch.cat([x[dst], x[src]], -1)
        msg = self.pre_nns[0](z)
        deg = torch.bincount(dst, minlength=n).clamp(min=1).float()[:, None]
        s = torch.zeros(n, f).index_add_(0, dst, msg)
        sq = torch.zeros(n, f).index_add_(0, dst, msg * msg)
        mean = s / deg
        std = torch.sqrt((sq / deg - mean * mean).clamp(min=0.0) + 1e-5)
        mn = torch.full((n, f), float("inf")).scatter_reduce_(
            0, dst[:, None].expand(-1, f), msg, "amin", include_self=True)
        mx = torch.full((n, f), float("-inf")).scatter_reduce_(
            0, dst[:, None].expand(-1, f), msg, "amax", include_self=True)
        agg = torch.cat([mean, mn, mx, std], -1)
        log_deg = torch.log(deg + 1.0)
        scaled = torch.cat([
            agg,
            agg * (log_deg / AVG_DEG_LOG),
            agg * (AVG_DEG_LOG / log_deg),
            agg * (deg / AVG_DEG_LIN),
        ], -1)
        out = self.post_nns[0](torch.cat([x, scaled], -1))
        return self.lin(out)


class TwinCGCNN(tnn.Module):
    def __init__(self, din, dout):
        super().__init__()
        assert din == dout
        self.lin_f = tnn.Linear(2 * din, dout)
        self.lin_s = tnn.Linear(2 * din, dout)

    def forward(self, x, ei, pos):
        src, dst = ei
        z = torch.cat([x[dst], x[src]], -1)
        m = torch.sigmoid(self.lin_f(z)) * torch.nn.functional.softplus(
            self.lin_s(z))
        return x + torch.zeros_like(x).index_add_(0, dst, m)


class _PygSeqWrap(tnn.Module):
    """Emulates torch_geometric.nn.Sequential child naming (module_{i})."""

    def __init__(self, conv, slot=0):
        super().__init__()
        setattr(self, f"module_{slot}", conv)
        self._slot = slot

    def forward(self, *a):
        return getattr(self, f"module_{self._slot}")(*a)


class _BNWrap(tnn.Module):
    """Emulates PyG BatchNorm (wraps torch BatchNorm1d as .module)."""

    def __init__(self, dim):
        super().__init__()
        self.module = tnn.BatchNorm1d(dim)

    def forward(self, x):
        return self.module(x)


class TorchTwinModel(tnn.Module):
    """Reference-keyed skeleton: graph_convs / feature_layers /
    graph_shared / heads_NN (reference Base.py:50-279)."""

    def __init__(self, conv_cls, with_bn, heads, num_layers=2,
                 shared=(4, 4), headlayers=(4, 4), seq_slot=0,
                 in_dim=IN_DIM):
        super().__init__()
        self.graph_convs = tnn.ModuleList()
        self.feature_layers = tnn.ModuleList()
        dims = [(in_dim, HIDDEN)] + [(HIDDEN, HIDDEN)] * (num_layers - 1)
        for din, dout in dims:
            self.graph_convs.append(_PygSeqWrap(conv_cls(din, dout), seq_slot))
            self.feature_layers.append(
                _BNWrap(dout) if with_bn else tnn.Identity())
        layers = [tnn.Linear(HIDDEN, shared[0]), tnn.ReLU()]
        for i in range(len(shared) - 1):
            layers += [tnn.Linear(shared[i], shared[i + 1]), tnn.ReLU()]
        self.graph_shared = tnn.Sequential(*layers)
        self.heads_NN = tnn.ModuleList()
        self.head_types = heads
        for htype in heads:
            if htype == "graph":
                hl = [tnn.Linear(shared[-1], headlayers[0]), tnn.ReLU()]
                for i in range(len(headlayers) - 1):
                    hl += [tnn.Linear(headlayers[i], headlayers[i + 1]),
                           tnn.ReLU()]
                hl += [tnn.Linear(headlayers[-1], 1)]
                self.heads_NN.append(tnn.Sequential(*hl))
            else:  # shared node MLP (MLPNode, Base.py:383-394)
                mlp = tnn.Sequential(
                    tnn.Linear(HIDDEN, headlayers[0]), tnn.ReLU(),
                    tnn.Linear(headlayers[0], headlayers[1]), tnn.ReLU(),
                    tnn.Linear(headlayers[1], 1))
                holder = tnn.Module()
                holder.mlp = tnn.ModuleList([mlp])
                self.heads_NN.append(holder)

    def forward(self, x, ei, pos, gid, n_graphs):
        for conv, fl in zip(self.graph_convs, self.feature_layers):
            x = conv(x, ei, pos)
            x = fl(x)
            x = torch.relu(x)
        counts = torch.bincount(gid, minlength=n_graphs).clamp(min=1).float()
        pooled = torch.zeros(n_graphs, x.shape[1]).index_add_(0, gid, x)
        pooled = pooled / counts[:, None]
        z = self.graph_shared(pooled)
        outs = []
        for htype, head in zip(self.head_types, self.heads_NN):
            if htype == "graph":
                outs.append(head(z))
            else:
                outs.append(head.mlp[0](x))
        return outs


# ---------------------------------------------------------------------------
# harness
# ---------------------------------------------------------------------------


def _make_batch(in_dim=IN_DIM, heads=("graph",)):
    rng = np.random.RandomState(0)
    samples = []
    for _ in range(N_GRAPHS):
        pos = rng.rand(N_NODES, 3).astype(np.float32) * 1.5
        x = rng.rand(N_NODES, in_dim).astype(np.float32)
        ei = radius_graph(pos, radius=3.0, max_neighbours=10)
        samples.append(GraphSample(
            x=x, pos=pos, edge_index=ei,
            graph_y=np.asarray([x.sum()], np.float32), node_y=x[:, :1]))
    specs = [HeadSpec(f"h{i}", t, 1) for i, t in enumerate(heads)]
    pad = PadSpec.for_batch(N_GRAPHS, N_NODES,
                            max(s.num_edges for s in samples) + 4)
    return collate(samples, pad, specs), samples


def _flax_cfg(model_type, heads=("graph",)):
    return ModelConfig(
        model_type=model_type,
        input_dim=HIDDEN if model_type == "CGCNN" else IN_DIM,
        hidden_dim=HIDDEN,
        output_dim=tuple(1 for _ in heads),
        output_type=tuple(heads),
        graph_head=GraphHeadCfg(2, 4, 2, (4, 4)),
        node_head=NodeHeadCfg(2, (4, 4), "mlp"),
        task_weights=tuple(1.0 for _ in heads),
        num_conv_layers=2,
        num_gaussians=6,
        num_filters=HIDDEN,
        radius=3.0,
        max_neighbours=10,
        max_degree=10,
        pna_avg_deg_log=AVG_DEG_LOG,
        pna_avg_deg_lin=AVG_DEG_LIN,
    )


def _randomize(sd, seed=0):
    g = torch.Generator().manual_seed(seed)
    out = {}
    for k, v in sd.items():
        if "running_var" in k:
            out[k] = torch.rand(v.shape, generator=g) * 0.5 + 0.75
        elif "num_batches_tracked" in k:
            out[k] = v
        else:
            out[k] = torch.randn(v.shape, generator=g) * 0.3
    return out


_TWINS = {
    "SAGE": (TwinSAGE, True),
    "GIN": (TwinGIN, True),
    "PNA": (TwinPNA, True),
    "SchNet": (TwinSchNet, False),
    "CGCNN": (TwinCGCNN, True),
}


def _run_parity(model_type, heads=("graph",), seq_slot=0, tmp_path=None):
    conv_cls, with_bn = _TWINS[model_type]
    twin = TorchTwinModel(
        conv_cls, with_bn, heads, seq_slot=seq_slot,
        in_dim=HIDDEN if model_type == "CGCNN" else IN_DIM)
    sd = _randomize(twin.state_dict())
    twin.load_state_dict(sd)
    twin.eval()

    batch, samples = _make_batch(
        in_dim=HIDDEN if model_type == "CGCNN" else IN_DIM, heads=heads)
    cfg = _flax_cfg(model_type, heads)
    model = create_model(cfg)
    template = init_model(model, batch)

    if tmp_path is not None:
        path = str(tmp_path / "ref.pk")
        torch.save({"model_state_dict": sd}, path)
        variables = port_checkpoint(path, model_type, template)
    else:
        variables = port_state_dict(sd, model_type, template)

    flax_out = model.apply(variables, batch, False)

    # torch twin on the real (unpadded) concatenation
    em = np.asarray(batch.edge_mask) > 0
    nm = np.asarray(batch.node_mask) > 0
    gm = np.asarray(batch.graph_mask) > 0
    x = torch.tensor(np.asarray(batch.x)[nm])
    pos = torch.tensor(np.asarray(batch.pos)[nm])
    ei = torch.tensor(np.stack([
        np.asarray(batch.senders)[em], np.asarray(batch.receivers)[em]]))
    gid = torch.tensor(np.asarray(batch.node_gid)[nm])
    with torch.no_grad():
        t_out = twin(x, ei, pos, gid, int(gm.sum()))

    for ih, htype in enumerate(heads):
        ours = np.asarray(flax_out[ih])
        ours = ours[gm] if htype == "graph" else ours[nm]
        theirs = t_out[ih].numpy()
        np.testing.assert_allclose(ours, theirs, atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("model_type", sorted(_TWINS))
def test_forward_parity(model_type):
    _run_parity(model_type)


def test_parity_multihead_node_mlp():
    _run_parity("SAGE", heads=("graph", "node"))


def test_parity_through_checkpoint_file(tmp_path):
    _run_parity("SchNet", tmp_path=tmp_path)


def test_pygseq_nesting_depth_irrelevant():
    # reference SchNet convs sit at Sequential slot 2 (after the
    # interaction graph and distance expansion modules, SCFStack.py:96-116)
    _run_parity("SchNet", seq_slot=2)


def test_unsupported_arch_raises():
    with pytest.raises(NotImplementedError):
        port_state_dict({}, "EGNN", {"params": {}})
