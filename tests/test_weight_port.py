"""Forward parity for the torch->flax weight converter (tools/port_weights).

Strategy: PyG is not installed here, so each supported arch gets a plain-
torch twin whose state_dict keys match the reference checkpoint layout
exactly (``graph_convs.{i}.module_0.*`` PyGSeq nesting included, reference
hydragnn/utils/model.py:58-103 checkpoint format, Base.py:200-279 head
naming) and whose math mirrors the documented conv semantics.  A random
twin checkpoint ported through ``port_state_dict`` must reproduce the flax
model's predictions to 1e-4 — this validates every row of docs/WEIGHTS.md
(transposes, bias placement, Sequential slot arithmetic, BN stats split)
end to end.
"""

import math

import numpy as np
import pytest
import torch
import torch.nn as tnn

from hydragnn_tpu.graph.batch import GraphSample, HeadSpec, PadSpec, collate
from hydragnn_tpu.graph.neighborlist import radius_graph
from hydragnn_tpu.models.base import (
    GraphHeadCfg,
    ModelConfig,
    NodeHeadCfg,
)
from hydragnn_tpu.models.create import create_model, init_model
from tools.port_weights import port_checkpoint, port_state_dict

HIDDEN = 8
IN_DIM = 3
N_NODES = 5
N_GRAPHS = 3
AVG_DEG_LOG = 1.3
AVG_DEG_LIN = 3.5


# ---------------------------------------------------------------------------
# plain-torch twins (reference-keyed state dicts, documented math)
# ---------------------------------------------------------------------------


class TwinSAGE(tnn.Module):
    def __init__(self, din, dout):
        super().__init__()
        self.lin_l = tnn.Linear(din, dout)           # aggregated neighbors
        self.lin_r = tnn.Linear(din, dout, bias=False)  # root

    def forward(self, x, ei, pos):
        src, dst = ei
        deg = torch.bincount(dst, minlength=x.shape[0]).clamp(min=1)
        agg = torch.zeros(x.shape[0], x.shape[1]).index_add_(0, dst, x[src])
        agg = agg / deg[:, None]
        return self.lin_l(agg) + self.lin_r(x)


class TwinGIN(tnn.Module):
    def __init__(self, din, dout):
        super().__init__()
        self.nn = tnn.Sequential(
            tnn.Linear(din, dout), tnn.ReLU(), tnn.Linear(dout, dout))
        self.eps = tnn.Parameter(torch.tensor(100.0))

    def forward(self, x, ei, pos):
        src, dst = ei
        agg = torch.zeros_like(x).index_add_(0, dst, x[src])
        return self.nn((1.0 + self.eps) * x + agg)


class TwinSchNet(tnn.Module):
    def __init__(self, din, dout, num_gaussians=6, num_filters=HIDDEN,
                 cutoff=3.0):
        super().__init__()
        self.nn = tnn.Sequential(
            tnn.Linear(num_gaussians, num_filters), tnn.Identity(),
            tnn.Linear(num_filters, num_filters))
        self.lin1 = tnn.Linear(din, num_filters, bias=False)
        self.lin2 = tnn.Linear(num_filters, dout)
        self.num_gaussians, self.cutoff = num_gaussians, cutoff

    def forward(self, x, ei, pos):
        src, dst = ei
        d = pos[src] - pos[dst]
        w = torch.sqrt((d * d).sum(-1) + 1e-12)
        off = torch.linspace(0.0, self.cutoff, self.num_gaussians)
        coeff = -0.5 / float(off[1] - off[0]) ** 2
        rbf = torch.exp(coeff * (w[:, None] - off[None, :]) ** 2)
        cut = 0.5 * (torch.cos(w * math.pi / self.cutoff) + 1.0)
        cut = torch.where(w <= self.cutoff, cut, torch.zeros_like(cut))
        filt = self.nn[2](_ssp(self.nn[0](rbf))) * cut[:, None]
        h = self.lin1(x)
        msg = h[src] * filt
        agg = torch.zeros(x.shape[0], h.shape[1]).index_add_(0, dst, msg)
        return self.lin2(agg)


def _ssp(x):
    return torch.nn.functional.softplus(x) - math.log(2.0)


class TwinPNA(tnn.Module):
    def __init__(self, din, dout):
        super().__init__()
        self.pre_nns = tnn.ModuleList([tnn.Sequential(tnn.Linear(2 * din, din))])
        self.post_nns = tnn.ModuleList(
            [tnn.Sequential(tnn.Linear(din + 16 * din, dout))])
        self.lin = tnn.Linear(dout, dout)

    def forward(self, x, ei, pos):
        src, dst = ei
        n, f = x.shape
        z = torch.cat([x[dst], x[src]], -1)
        msg = self.pre_nns[0](z)
        deg = torch.bincount(dst, minlength=n).clamp(min=1).float()[:, None]
        s = torch.zeros(n, f).index_add_(0, dst, msg)
        sq = torch.zeros(n, f).index_add_(0, dst, msg * msg)
        mean = s / deg
        std = torch.sqrt((sq / deg - mean * mean).clamp(min=0.0) + 1e-5)
        mn = torch.full((n, f), float("inf")).scatter_reduce_(
            0, dst[:, None].expand(-1, f), msg, "amin", include_self=True)
        mx = torch.full((n, f), float("-inf")).scatter_reduce_(
            0, dst[:, None].expand(-1, f), msg, "amax", include_self=True)
        agg = torch.cat([mean, mn, mx, std], -1)
        log_deg = torch.log(deg + 1.0)
        scaled = torch.cat([
            agg,
            agg * (log_deg / AVG_DEG_LOG),
            agg * (AVG_DEG_LOG / log_deg),
            agg * (deg / AVG_DEG_LIN),
        ], -1)
        out = self.post_nns[0](torch.cat([x, scaled], -1))
        return self.lin(out)


class TwinCGCNN(tnn.Module):
    def __init__(self, din, dout):
        super().__init__()
        assert din == dout
        self.lin_f = tnn.Linear(2 * din, dout)
        self.lin_s = tnn.Linear(2 * din, dout)

    def forward(self, x, ei, pos):
        src, dst = ei
        z = torch.cat([x[dst], x[src]], -1)
        m = torch.sigmoid(self.lin_f(z)) * torch.nn.functional.softplus(
            self.lin_s(z))
        return x + torch.zeros_like(x).index_add_(0, dst, m)


class _PygSeqWrap(tnn.Module):
    """Emulates torch_geometric.nn.Sequential child naming (module_{i})."""

    def __init__(self, conv, slot=0):
        super().__init__()
        setattr(self, f"module_{slot}", conv)
        self._slot = slot

    def forward(self, *a):
        return getattr(self, f"module_{self._slot}")(*a)


class _BNWrap(tnn.Module):
    """Emulates PyG BatchNorm (wraps torch BatchNorm1d as .module)."""

    def __init__(self, dim):
        super().__init__()
        self.module = tnn.BatchNorm1d(dim)

    def forward(self, x):
        return self.module(x)


class TorchTwinModel(tnn.Module):
    """Reference-keyed skeleton: graph_convs / feature_layers /
    graph_shared / heads_NN (reference Base.py:50-279)."""

    def __init__(self, conv_cls, with_bn, heads, num_layers=2,
                 shared=(4, 4), headlayers=(4, 4), seq_slot=0,
                 in_dim=IN_DIM):
        super().__init__()
        self.graph_convs = tnn.ModuleList()
        self.feature_layers = tnn.ModuleList()
        dims = [(in_dim, HIDDEN)] + [(HIDDEN, HIDDEN)] * (num_layers - 1)
        for din, dout in dims:
            self.graph_convs.append(_PygSeqWrap(conv_cls(din, dout), seq_slot))
            self.feature_layers.append(
                _BNWrap(dout) if with_bn else tnn.Identity())
        layers = [tnn.Linear(HIDDEN, shared[0]), tnn.ReLU()]
        for i in range(len(shared) - 1):
            layers += [tnn.Linear(shared[i], shared[i + 1]), tnn.ReLU()]
        self.graph_shared = tnn.Sequential(*layers)
        self.heads_NN = tnn.ModuleList()
        self.head_types = heads
        for htype in heads:
            if htype == "graph":
                hl = [tnn.Linear(shared[-1], headlayers[0]), tnn.ReLU()]
                for i in range(len(headlayers) - 1):
                    hl += [tnn.Linear(headlayers[i], headlayers[i + 1]),
                           tnn.ReLU()]
                hl += [tnn.Linear(headlayers[-1], 1)]
                self.heads_NN.append(tnn.Sequential(*hl))
            else:  # shared node MLP (MLPNode, Base.py:383-394)
                mlp = tnn.Sequential(
                    tnn.Linear(HIDDEN, headlayers[0]), tnn.ReLU(),
                    tnn.Linear(headlayers[0], headlayers[1]), tnn.ReLU(),
                    tnn.Linear(headlayers[1], 1))
                holder = tnn.Module()
                holder.mlp = tnn.ModuleList([mlp])
                self.heads_NN.append(holder)

    def forward(self, x, ei, pos, gid, n_graphs):
        for conv, fl in zip(self.graph_convs, self.feature_layers):
            x = conv(x, ei, pos)
            x = fl(x)
            x = torch.relu(x)
        counts = torch.bincount(gid, minlength=n_graphs).clamp(min=1).float()
        pooled = torch.zeros(n_graphs, x.shape[1]).index_add_(0, gid, x)
        pooled = pooled / counts[:, None]
        z = self.graph_shared(pooled)
        outs = []
        for htype, head in zip(self.head_types, self.heads_NN):
            if htype == "graph":
                outs.append(head(z))
            else:
                outs.append(head.mlp[0](x))
        return outs


# ---------------------------------------------------------------------------
# harness
# ---------------------------------------------------------------------------


def _make_batch(in_dim=IN_DIM, heads=("graph",)):
    rng = np.random.RandomState(0)
    samples = []
    for _ in range(N_GRAPHS):
        pos = rng.rand(N_NODES, 3).astype(np.float32) * 1.5
        x = rng.rand(N_NODES, in_dim).astype(np.float32)
        ei = radius_graph(pos, radius=3.0, max_neighbours=10)
        samples.append(GraphSample(
            x=x, pos=pos, edge_index=ei,
            graph_y=np.asarray([x.sum()], np.float32), node_y=x[:, :1]))
    specs = [HeadSpec(f"h{i}", t, 1) for i, t in enumerate(heads)]
    pad = PadSpec.for_batch(N_GRAPHS, N_NODES,
                            max(s.num_edges for s in samples) + 4)
    return collate(samples, pad, specs), samples


def _flax_cfg(model_type, heads=("graph",)):
    return ModelConfig(
        model_type=model_type,
        input_dim=HIDDEN if model_type == "CGCNN" else IN_DIM,
        hidden_dim=HIDDEN,
        output_dim=tuple(1 for _ in heads),
        output_type=tuple(heads),
        graph_head=GraphHeadCfg(2, 4, 2, (4, 4)),
        node_head=NodeHeadCfg(2, (4, 4), "mlp"),
        task_weights=tuple(1.0 for _ in heads),
        num_conv_layers=2,
        num_gaussians=6,
        num_filters=HIDDEN,
        radius=3.0,
        max_neighbours=10,
        max_degree=10,
        pna_avg_deg_log=AVG_DEG_LOG,
        pna_avg_deg_lin=AVG_DEG_LIN,
        num_radial=6,
        num_spherical=7,
        basis_emb_size=8,
        int_emb_size=16,
        out_emb_size=16,
        envelope_exponent=5,
        num_before_skip=1,
        num_after_skip=2,
    )


def _randomize(sd, seed=0):
    g = torch.Generator().manual_seed(seed)
    out = {}
    for k, v in sd.items():
        if "running_var" in k:
            out[k] = torch.rand(v.shape, generator=g) * 0.5 + 0.75
        elif "num_batches_tracked" in k:
            out[k] = v
        else:
            out[k] = torch.randn(v.shape, generator=g) * 0.3
    return out


_TWINS = {
    "SAGE": (TwinSAGE, True),
    "GIN": (TwinGIN, True),
    "PNA": (TwinPNA, True),
    "SchNet": (TwinSchNet, False),
    "CGCNN": (TwinCGCNN, True),
}


def _run_parity(model_type, heads=("graph",), seq_slot=0, tmp_path=None):
    conv_cls, with_bn = _TWINS[model_type]
    twin = TorchTwinModel(
        conv_cls, with_bn, heads, seq_slot=seq_slot,
        in_dim=HIDDEN if model_type == "CGCNN" else IN_DIM)
    sd = _randomize(twin.state_dict())
    twin.load_state_dict(sd)
    twin.eval()

    batch, samples = _make_batch(
        in_dim=HIDDEN if model_type == "CGCNN" else IN_DIM, heads=heads)
    cfg = _flax_cfg(model_type, heads)
    model = create_model(cfg)
    template = init_model(model, batch)

    if tmp_path is not None:
        path = str(tmp_path / "ref.pk")
        torch.save({"model_state_dict": sd}, path)
        variables = port_checkpoint(path, model_type, template)
    else:
        variables = port_state_dict(sd, model_type, template)

    flax_out = model.apply(variables, batch, False)

    # torch twin on the real (unpadded) concatenation
    em = np.asarray(batch.edge_mask) > 0
    nm = np.asarray(batch.node_mask) > 0
    gm = np.asarray(batch.graph_mask) > 0
    x = torch.tensor(np.asarray(batch.x)[nm])
    pos = torch.tensor(np.asarray(batch.pos)[nm])
    ei = torch.tensor(np.stack([
        np.asarray(batch.senders)[em], np.asarray(batch.receivers)[em]]))
    gid = torch.tensor(np.asarray(batch.node_gid)[nm])
    with torch.no_grad():
        t_out = twin(x, ei, pos, gid, int(gm.sum()))

    for ih, htype in enumerate(heads):
        ours = np.asarray(flax_out[ih])
        ours = ours[gm] if htype == "graph" else ours[nm]
        theirs = t_out[ih].numpy()
        np.testing.assert_allclose(ours, theirs, atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("model_type", sorted(_TWINS))
def test_forward_parity(model_type):
    _run_parity(model_type)


def test_parity_multihead_node_mlp():
    _run_parity("SAGE", heads=("graph", "node"))


def test_parity_through_checkpoint_file(tmp_path):
    _run_parity("SchNet", tmp_path=tmp_path)


def test_pygseq_nesting_depth_irrelevant():
    # reference SchNet convs sit at Sequential slot 2 (after the
    # interaction graph and distance expansion modules, SCFStack.py:96-116)
    _run_parity("SchNet", seq_slot=2)


def test_unsupported_arch_raises():
    with pytest.raises(NotImplementedError):
        port_state_dict({}, "NotAnArch", {"params": {}})


# ---------------------------------------------------------------------------
# round-3 twins: GAT, EGNN, MFC, DimeNet (converter now covers all 9 archs
# except none — SURVEY §2 parity for checkpoint migration)
# ---------------------------------------------------------------------------

GAT_HEADS, GAT_SLOPE = 6, 0.05


class TwinGATConv(tnn.Module):
    def __init__(self, din, dout, concat):
        super().__init__()
        h, f = GAT_HEADS, dout
        self.lin_l = tnn.Linear(din, h * f)
        self.lin_r = tnn.Linear(din, h * f)
        self.att = tnn.Parameter(torch.randn(1, h, f))
        self.bias = tnn.Parameter(torch.zeros(h * f if concat else f))
        self.concat = concat

    def forward(self, x, ei, pos):
        src, dst = ei
        n = x.shape[0]
        h, f = GAT_HEADS, self.att.shape[-1]
        xl, xr = self.lin_l(x), self.lin_r(x)

        def logits(s, t):
            z = torch.nn.functional.leaky_relu(s + t, GAT_SLOPE)
            return (z.reshape(-1, h, f) * self.att).sum(-1)

        e_edge = logits(xl[src], xr[dst])
        e_self = logits(xl, xr)
        seg_max = torch.full((n, h), -1e9).scatter_reduce_(
            0, dst[:, None].expand(-1, h), e_edge, "amax", include_self=True)
        seg_max = torch.where(seg_max <= -5e8, torch.zeros_like(seg_max),
                              seg_max)
        deg = torch.bincount(dst, minlength=n)
        seg_max = torch.where(deg[:, None] > 0, seg_max, e_self)
        seg_max = torch.maximum(seg_max, e_self)
        exp_edge = torch.exp(e_edge - seg_max[dst])
        exp_self = torch.exp(e_self - seg_max)
        denom = torch.zeros(n, h).index_add_(0, dst, exp_edge) + exp_self
        a_edge = exp_edge / denom.clamp(min=1e-16)[dst]
        a_self = exp_self / denom.clamp(min=1e-16)
        msg = a_edge[:, :, None] * xl[src].reshape(-1, h, f)
        out = torch.zeros(n, h, f).index_add_(0, dst, msg)
        out = out + a_self[:, :, None] * xl.reshape(n, h, f)
        if self.concat:
            return out.reshape(n, h * f) + self.bias
        return out.mean(1) + self.bias


class TwinGATModel(tnn.Module):
    """GAT needs its own skeleton: concat layers widen features to
    hidden*heads and BN tracks that width (reference GATStack.py:35-46)."""

    def __init__(self):
        super().__init__()
        h = GAT_HEADS
        self.graph_convs = tnn.ModuleList([
            _PygSeqWrap(TwinGATConv(IN_DIM, HIDDEN, True)),
            _PygSeqWrap(TwinGATConv(HIDDEN * h, HIDDEN, False)),
        ])
        self.feature_layers = tnn.ModuleList(
            [_BNWrap(HIDDEN * h), _BNWrap(HIDDEN)])
        self.graph_shared = tnn.Sequential(
            tnn.Linear(HIDDEN, 4), tnn.ReLU(), tnn.Linear(4, 4), tnn.ReLU())
        self.heads_NN = tnn.ModuleList([tnn.Sequential(
            tnn.Linear(4, 4), tnn.ReLU(), tnn.Linear(4, 4), tnn.ReLU(),
            tnn.Linear(4, 1))])

    def forward(self, x, ei, pos, gid, n_graphs):
        for conv, fl in zip(self.graph_convs, self.feature_layers):
            x = torch.relu(fl(conv(x, ei, pos)))
        counts = torch.bincount(gid, minlength=n_graphs).clamp(min=1).float()
        pooled = torch.zeros(n_graphs, x.shape[1]).index_add_(0, gid, x)
        z = self.graph_shared(pooled / counts[:, None])
        return [self.heads_NN[0](z)]


class TwinEGNN(tnn.Module):
    def __init__(self, din, dout, hidden=HIDDEN):
        super().__init__()
        self.edge_mlp = tnn.Sequential(
            tnn.Linear(2 * din + 1, hidden), tnn.ReLU(),
            tnn.Linear(hidden, hidden), tnn.ReLU())
        self.node_mlp = tnn.Sequential(
            tnn.Linear(din + hidden, hidden), tnn.ReLU(),
            tnn.Linear(hidden, dout))

    def forward(self, x, ei, pos):
        src, dst = ei
        diff = pos[src] - pos[dst]
        radial = (diff * diff).sum(-1, keepdim=True)
        m = torch.cat([x[src], x[dst], radial], -1)
        m = self.edge_mlp(m)
        agg = torch.zeros(x.shape[0], m.shape[1]).index_add_(0, src, m)
        return self.node_mlp(torch.cat([x, agg], -1))


class TwinMFC(tnn.Module):
    def __init__(self, din, dout, max_degree=10):
        super().__init__()
        self.lins_l = tnn.ModuleList(
            [tnn.Linear(din, dout) for _ in range(max_degree + 1)])
        self.lins_r = tnn.ModuleList(
            [tnn.Linear(din, dout, bias=False) for _ in range(max_degree + 1)])
        self.max_degree = max_degree

    def forward(self, x, ei, pos):
        src, dst = ei
        n = x.shape[0]
        deg = torch.bincount(dst, minlength=n).clamp(max=self.max_degree)
        agg = torch.zeros_like(x).index_add_(0, dst, x[src])
        out = torch.zeros(n, self.lins_l[0].out_features)
        for d in range(self.max_degree + 1):
            sel = deg == d
            if sel.any():
                out[sel] = self.lins_l[d](agg[sel]) + self.lins_r[d](x[sel])
        return out


def test_forward_parity_gat():
    twin = TwinGATModel()
    sd = _randomize(twin.state_dict(), seed=4)
    twin.load_state_dict(sd)
    twin.eval()

    batch, _ = _make_batch()
    cfg = _flax_cfg("GAT")
    model = create_model(cfg)
    template = init_model(model, batch)
    variables = port_state_dict(sd, "GAT", template)
    flax_out = model.apply(variables, batch, False)

    em = np.asarray(batch.edge_mask) > 0
    nm = np.asarray(batch.node_mask) > 0
    gm = np.asarray(batch.graph_mask) > 0
    with torch.no_grad():
        t_out = twin(
            torch.tensor(np.asarray(batch.x)[nm]),
            torch.tensor(np.stack([np.asarray(batch.senders)[em],
                                   np.asarray(batch.receivers)[em]])),
            torch.tensor(np.asarray(batch.pos)[nm]),
            torch.tensor(np.asarray(batch.node_gid)[nm]), int(gm.sum()))
    np.testing.assert_allclose(np.asarray(flax_out[0])[gm],
                               t_out[0].numpy(), atol=1e-4, rtol=1e-4)


@pytest.mark.parametrize("model_type,conv_cls,with_bn",
                         [("EGNN", TwinEGNN, False), ("MFC", TwinMFC, True)])
def test_forward_parity_round3(model_type, conv_cls, with_bn):
    _TWINS[model_type] = (conv_cls, with_bn)
    try:
        _run_parity(model_type)
    finally:
        _TWINS.pop(model_type)


class TwinDimeNetConv(tnn.Module):
    """One DIMEStack conv keyed like the reference PyGSeq
    (module_0 = input Linear, module_1 = HydraEmbeddingBlock, module_2 =
    InteractionPPBlock, module_3 = OutputPPBlock; DIMEStack.py:79-116).
    Geometry featurization (rbf/sbf/triplets) is fed in precomputed — the
    twin validates the WEIGHT mapping; basis math carries no weights
    except the stack-level rbf.freq handled by the model twin."""

    def __init__(self, din, hidden, num_radial=6, num_spherical=7,
                 basis_emb=8, int_emb=16, out_emb=16, out_dim=HIDDEN):
        super().__init__()
        sbf_dim = num_radial * num_spherical
        m0 = tnn.Linear(din, hidden)
        m1 = tnn.Module()
        m1.lin_rbf = tnn.Linear(num_radial, hidden)
        m1.lin = tnn.Linear(3 * hidden, hidden)
        m2 = tnn.Module()
        m2.lin_ji = tnn.Linear(hidden, hidden)
        m2.lin_kj = tnn.Linear(hidden, hidden)
        m2.lin_rbf1 = tnn.Linear(num_radial, basis_emb, bias=False)
        m2.lin_rbf2 = tnn.Linear(basis_emb, hidden, bias=False)
        m2.lin_sbf1 = tnn.Linear(sbf_dim, basis_emb, bias=False)
        m2.lin_sbf2 = tnn.Linear(basis_emb, int_emb, bias=False)
        m2.lin_down = tnn.Linear(hidden, int_emb, bias=False)
        m2.lin_up = tnn.Linear(int_emb, hidden, bias=False)
        m2.lin = tnn.Linear(hidden, hidden)
        m2.layers_before_skip = tnn.ModuleList()
        m2.layers_after_skip = tnn.ModuleList()
        for lst, cnt in ((m2.layers_before_skip, 1),
                         (m2.layers_after_skip, 2)):
            for _ in range(cnt):
                res = tnn.Module()
                res.lin1 = tnn.Linear(hidden, hidden)
                res.lin2 = tnn.Linear(hidden, hidden)
                lst.append(res)
        m3 = tnn.Module()
        m3.lin_rbf = tnn.Linear(num_radial, hidden, bias=False)
        m3.lin_up = tnn.Linear(hidden, out_emb, bias=False)
        m3.lins = tnn.ModuleList([tnn.Linear(out_emb, out_emb)])
        m3.lin = tnn.Linear(out_emb, out_dim, bias=False)
        for i, m in enumerate((m0, m1, m2, m3)):
            setattr(self, f"module_{i}", m)

    def forward(self, x, ei, rbf, sbf, idx_kj, idx_ji):
        silu = torch.nn.functional.silu
        src, dst = ei
        e = src.shape[0]
        h = self.module_0(x)
        rbf_e = silu(self.module_1.lin_rbf(rbf))
        x1 = silu(self.module_1.lin(torch.cat([h[dst], h[src], rbf_e], -1)))

        m2 = self.module_2
        x_ji = silu(m2.lin_ji(x1))
        x_kj = silu(m2.lin_kj(x1))
        x_kj = x_kj * m2.lin_rbf2(m2.lin_rbf1(rbf))
        x_kj = silu(m2.lin_down(x_kj))
        sbf2 = m2.lin_sbf2(m2.lin_sbf1(sbf))
        msg = x_kj[idx_kj] * sbf2
        agg = torch.zeros(e, msg.shape[1]).index_add_(0, idx_ji, msg)
        x_kj = silu(m2.lin_up(agg))
        hh = x_ji + x_kj
        for res in m2.layers_before_skip:
            hh = hh + silu(res.lin2(silu(res.lin1(hh))))
        hh = silu(m2.lin(hh)) + x1
        for res in m2.layers_after_skip:
            hh = hh + silu(res.lin2(silu(res.lin1(hh))))

        m3 = self.module_3
        z = m3.lin_rbf(rbf) * hh
        nodes = torch.zeros(x.shape[0], z.shape[1]).index_add_(0, dst, z)
        nodes = m3.lin_up(nodes)
        for lin in m3.lins:
            nodes = silu(lin(nodes))
        return m3.lin(nodes)


def test_forward_parity_dimenet():
    import jax.numpy as jnp

    from hydragnn_tpu.models.dimenet import (
        add_dimenet_extras, count_triplets, envelope, spherical_basis)

    batch, _ = _make_batch()
    real_e = np.asarray(batch.edge_mask) > 0
    ei_real = np.stack([np.asarray(batch.senders)[real_e],
                        np.asarray(batch.receivers)[real_e]])
    t = count_triplets(ei_real, batch.x.shape[0])
    batch = add_dimenet_extras(batch, max_triplets=t + 4)

    cfg = _flax_cfg("DimeNet")
    model = create_model(cfg)
    template = init_model(model, batch)

    # twin keyed like the reference, plus the stack-level shared rbf.freq
    # DIMEStack: hidden = out_dim if in_dim == 1 else in_dim
    # (DIMEStack.py:80) — conv0 runs at width IN_DIM, conv1 at HIDDEN
    twin_convs = tnn.ModuleList([
        _PygSeqWrap(TwinDimeNetConv(IN_DIM, IN_DIM), 9),
        _PygSeqWrap(TwinDimeNetConv(HIDDEN, HIDDEN), 9),
    ])
    # _PygSeqWrap(.., 9) keeps attr name unique; rename to the real layout
    sd = {}
    holder = tnn.Module()
    holder.graph_convs = twin_convs
    base_sd = holder.state_dict()
    for k, v in base_sd.items():
        sd[k.replace("module_9.", "")] = v
    g = torch.Generator().manual_seed(11)
    sd = {k: torch.randn(v.shape, generator=g) * 0.2 for k, v in sd.items()}
    sd["rbf.freq"] = torch.arange(1, 7).float() * math.pi \
        + torch.randn(6, generator=g) * 0.1
    # heads
    head_sd = _randomize(TorchTwinModel(
        TwinSAGE, False, ("graph",)).state_dict(), seed=12)
    for k, v in head_sd.items():
        if k.startswith(("graph_shared", "heads_NN")):
            sd[k] = v

    variables = port_state_dict(sd, "DimeNet", template)
    flax_out = model.apply(variables, batch, False)

    # twin forward on the real sub-arrays with geometry precomputed the
    # same way the flax model computes it
    em, nm, gm = (np.asarray(batch.edge_mask) > 0,
                  np.asarray(batch.node_mask) > 0,
                  np.asarray(batch.graph_mask) > 0)
    # map padded-node ids down to the compact real-node indexing
    pos = np.asarray(batch.pos)
    srcs = np.asarray(batch.senders)[em]
    dsts = np.asarray(batch.receivers)[em]
    dist = np.sqrt(((pos[dsts] - pos[srcs]) ** 2).sum(-1) + 1e-14)
    cutoff = 3.0
    freq = np.asarray(sd["rbf.freq"])
    d_scaled = dist[:, None] / cutoff
    rbf = np.asarray(envelope(jnp.asarray(d_scaled), 5)) * np.sin(
        freq[None, :] * d_scaled)

    tm = np.asarray(batch.extras["dn_triplet_mask"]) > 0
    tkj_g = np.asarray(batch.extras["dn_idx_kj"])[tm]
    tji_g = np.asarray(batch.extras["dn_idx_ji"])[tm]
    ti = np.asarray(batch.extras["dn_idx_i"])[tm]
    tj = np.asarray(batch.extras["dn_idx_j"])[tm]
    tk = np.asarray(batch.extras["dn_idx_k"])[tm]
    v_ji, v_ki = pos[tj] - pos[ti], pos[tk] - pos[ti]
    a = (v_ji * v_ki).sum(-1)
    b = np.linalg.norm(np.cross(v_ji, v_ki) + 1e-14, axis=-1)
    angle = np.arctan2(b, a)
    # global-edge-id -> real-edge-row mapping
    gid2row = -np.ones(batch.senders.shape[0], np.int64)
    gid2row[np.nonzero(em)[0]] = np.arange(em.sum())
    sbf = np.asarray(spherical_basis(
        jnp.asarray(dist / cutoff), jnp.asarray(angle),
        jnp.asarray(gid2row[tkj_g]), 7, 6, 5))

    x_t = torch.tensor(np.asarray(batch.x)[nm])
    # node ids in the padded batch ARE compact over real nodes only when
    # padding is trailing — assert and reuse directly
    assert nm[: nm.sum()].all()
    ei_t = torch.tensor(np.stack([srcs, dsts]))
    kj_t = torch.tensor(gid2row[tkj_g])
    ji_t = torch.tensor(gid2row[tji_g])
    rbf_t = torch.tensor(rbf, dtype=torch.float32)
    sbf_t = torch.tensor(sbf, dtype=torch.float32)

    holder2 = tnn.Module()
    holder2.graph_convs = twin_convs
    fixed = {}
    for k, v in sd.items():
        if k.startswith("graph_convs"):
            parts = k.split(".")
            fixed[".".join(parts[:2] + ["module_9"] + parts[2:])] = v
    holder2.load_state_dict(fixed, strict=False)
    for p in holder2.parameters():
        p.requires_grad_(False)

    x = x_t
    gid = torch.tensor(np.asarray(batch.node_gid)[nm])
    with torch.no_grad():
        for wrap in twin_convs:
            x = torch.relu(wrap.module_9(
                x, ei_t, rbf_t, sbf_t, kj_t, ji_t))
        counts = torch.bincount(gid, minlength=int(gm.sum())).clamp(min=1)
        pooled = torch.zeros(int(gm.sum()), x.shape[1]).index_add_(0, gid, x)
        pooled = pooled / counts[:, None].float()
        # run the heads through REAL twin modules loaded from sd — no
        # hand-rolled slot arithmetic to drift out of sync
        skel = TorchTwinModel(TwinSAGE, False, ("graph",))
        skel.load_state_dict(
            {k: v for k, v in sd.items()
             if k.startswith(("graph_shared", "heads_NN"))}, strict=False)
        skel.eval()
        z = skel.heads_NN[0](skel.graph_shared(pooled))

    np.testing.assert_allclose(
        np.asarray(flax_out[0])[gm], z.numpy(), atol=2e-4, rtol=2e-4)


class TwinEGNNEquivariant(TwinEGNN):
    """Adds the coord branch (reference E_GCL equivariant path,
    EGCLStack.py:160-173: Linear -> act -> bias-free Linear -> Tanh) and
    threads position updates like the stack does (all but the last layer)."""

    def __init__(self, din, dout, hidden=HIDDEN):
        super().__init__(din, dout, hidden)
        self.coord_mlp = tnn.Sequential(
            tnn.Linear(hidden, hidden), tnn.ReLU(),
            tnn.Linear(hidden, 1, bias=False), tnn.Tanh())

    def forward(self, x, ei, pos):
        src, dst = ei
        n = x.shape[0]
        diff = pos[src] - pos[dst]
        radial = (diff * diff).sum(-1, keepdim=True)
        diff_n = diff / (torch.sqrt(radial + 1e-12) + 1.0)
        m = self.edge_mlp(torch.cat([x[src], x[dst], radial], -1))
        c = self.coord_mlp(m)
        trans = torch.clamp(diff_n * c, -100.0, 100.0)
        deg = torch.bincount(src, minlength=n).clamp(min=1).float()
        mean_t = torch.zeros(n, 3).index_add_(0, src, trans) / deg[:, None]
        new_pos = pos + mean_t
        agg = torch.zeros(n, m.shape[1]).index_add_(0, src, m)
        return self.node_mlp(torch.cat([x, agg], -1)), new_pos


def test_forward_parity_egnn_equivariant():
    """Exercises the coord_mlp port path (square hidden x hidden kernels
    would otherwise port transposed without any shape error)."""
    import dataclasses

    twin = tnn.Module()
    twin.graph_convs = tnn.ModuleList([
        _PygSeqWrap(TwinEGNNEquivariant(IN_DIM, HIDDEN)),   # equivariant
        _PygSeqWrap(TwinEGNN(HIDDEN, HIDDEN)),              # last: not
    ])
    twin.feature_layers = tnn.ModuleList([tnn.Identity(), tnn.Identity()])
    skel = TorchTwinModel(TwinSAGE, False, ("graph",))
    twin.graph_shared = skel.graph_shared
    twin.heads_NN = skel.heads_NN
    sd = _randomize(twin.state_dict(), seed=21)
    twin.load_state_dict(sd)
    twin.eval()

    batch, _ = _make_batch()
    cfg = dataclasses.replace(_flax_cfg("EGNN"), equivariance=True)
    model = create_model(cfg)
    template = init_model(model, batch)
    assert "coord_mlp_0" in template["params"]["encoder_conv_0"]
    variables = port_state_dict(sd, "EGNN", template)
    flax_out = model.apply(variables, batch, False)

    em = np.asarray(batch.edge_mask) > 0
    nm = np.asarray(batch.node_mask) > 0
    gm = np.asarray(batch.graph_mask) > 0
    x = torch.tensor(np.asarray(batch.x)[nm])
    pos = torch.tensor(np.asarray(batch.pos)[nm])
    ei = torch.tensor(np.stack([np.asarray(batch.senders)[em],
                                np.asarray(batch.receivers)[em]]))
    gid = torch.tensor(np.asarray(batch.node_gid)[nm])
    with torch.no_grad():
        h, pos = twin.graph_convs[0](x, ei, pos)
        h = torch.relu(h)
        h2 = torch.relu(twin.graph_convs[1](h, ei, pos))
        n_graphs = int(gm.sum())
        counts = torch.bincount(gid, minlength=n_graphs).clamp(min=1).float()
        pooled = torch.zeros(n_graphs, h2.shape[1]).index_add_(0, gid, h2)
        z = twin.graph_shared(pooled / counts[:, None])
        out = twin.heads_NN[0](z)
    np.testing.assert_allclose(np.asarray(flax_out[0])[gm], out.numpy(),
                               atol=1e-4, rtol=1e-4)
