"""Formation-enthalpy conversion yields exactly 0 for linear data (parity:
reference tests/test_enthalpy.py:15-59)."""

import os

import numpy as np

from hydragnn_tpu.data.synthetic import deterministic_graph_data
from hydragnn_tpu.utils.lsms import convert_raw_data_energy_to_gibbs


def test_formation_enthalpy():
    d = "dataset/unit_test_enthalpy"
    os.makedirs(d, exist_ok=True)
    num_config = 10
    if not os.listdir(d):
        # random binary samples with linear (composition-proportional) energy
        deterministic_graph_data(
            d, num_config, number_types=2, linear_only=True, seed=11)
        # two pure-component configurations
        deterministic_graph_data(
            d, number_configurations=1, configuration_start=num_config,
            number_types=1, types=[0], linear_only=True, seed=12)
        deterministic_graph_data(
            d, number_configurations=1, configuration_start=num_config + 1,
            number_types=1, types=[1], linear_only=True, seed=13)

    convert_raw_data_energy_to_gibbs(d, [0, 1], create_plots=False)

    new_dir = d + "_gibbs_energy"
    for fname in os.listdir(new_dir):
        enthalpy = np.loadtxt(os.path.join(new_dir, fname), max_rows=1)
        assert enthalpy == 0
