"""HPO glue tests: built-in random search + halving over a tiny training,
launch-command builders (parity: reference qm9_hpo/optuna drivers and
utils/deephyper.py)."""

import json
import os

import pytest

import hydragnn_tpu
from hydragnn_tpu.hpo import HP, build_launch_command, read_node_list, run_hpo
from test_graphs import _generate_data


def test_run_hpo_random():
    with open(os.path.join(os.path.dirname(__file__), "inputs", "ci.json")) as f:
        config = json.load(f)
    config["NeuralNetwork"]["Architecture"]["model_type"] = "SAGE"
    config["NeuralNetwork"]["Training"]["num_epoch"] = 2
    _generate_data(config, num_samples_tot=60)

    space = [
        HP("lr", ["NeuralNetwork", "Training", "Optimizer", "learning_rate"],
           low=1e-3, high=3e-2, log=True),
        HP("hidden_dim", ["NeuralNetwork", "Architecture", "hidden_dim"],
           choices=[8, 16]),
    ]
    best, trials = run_hpo(config, space, n_trials=2, seed=0)
    assert len(trials) == 2
    assert best.value < float("inf")
    assert "lr" in best.params and "hidden_dim" in best.params


def test_launch_command_builders(monkeypatch):
    monkeypatch.delenv("SLURM_JOB_ID", raising=False)
    monkeypatch.delenv("SLURM_NODELIST", raising=False)
    monkeypatch.delenv("LSB_HOSTS", raising=False)
    assert read_node_list() == ["localhost"]

    monkeypatch.setenv("SLURM_NODELIST", "frontier[00001-00002]")
    assert read_node_list() == ["frontier00001", "frontier00002"]

    cmd = build_launch_command("trial.py", ["n1", "n2"], procs_per_node=4,
                               system="frontier", extra_args=["--lr", "0.1"])
    assert cmd[0] == "srun" and "-n" in cmd and "8" in cmd
    assert cmd[-2:] == ["--lr", "0.1"]

    cmd = build_launch_command("trial.py", ["localhost"], system="")
    assert cmd[0].endswith("python") or "python" in cmd[0]


def test_apply_hpo_args():
    from hydragnn_tpu.hpo import apply_hpo_args

    cfg = {"NeuralNetwork": {"Training": {"Optimizer": {"learning_rate": 1.0},
                                          "batch_size": 8}}}
    apply_hpo_args(cfg, [
        "NeuralNetwork.Training.Optimizer.learning_rate=0.005",
        "NeuralNetwork.Training.batch_size=16",
    ])
    assert cfg["NeuralNetwork"]["Training"]["Optimizer"]["learning_rate"] == 0.005
    assert cfg["NeuralNetwork"]["Training"]["batch_size"] == 16


def test_run_hpo_async_subprocess(tmp_path):
    """Async multi-job driver: concurrent subprocess trials, node-queue
    scheduling, val-loss scraping, hyperparameters passed as config paths
    (reference gfm_deephyper_multi.py:22-41)."""
    from hydragnn_tpu.hpo import HP, run_hpo_async

    trial = tmp_path / "trial.py"
    trial.write_text(
        "import argparse\n"
        "ap = argparse.ArgumentParser()\n"
        "ap.add_argument('--hpo', action='append', default=[])\n"
        "a = ap.parse_args()\n"
        "kv = dict(x.split('=') for x in a.hpo)\n"
        "lr = float(kv['Training.Optimizer.learning_rate'])\n"
        "print(f'val loss: {abs(lr - 0.01):.8f},')\n"
    )
    space = [HP("lr", ("Training", "Optimizer", "learning_rate"),
                low=1e-3, high=1e-1, log=True)]
    best, trials = run_hpo_async(
        str(trial), space, n_trials=6, n_concurrent=3,
        nodes=["localhost"], timeout=120)
    assert len(trials) == 6
    assert all(t.state == "complete" for t in trials)
    # objective = |lr - 0.01|: the best trial is the sampled lr nearest 0.01
    vals = {t.number: abs(t.params["lr"] - 0.01) for t in trials}
    assert best.value == pytest.approx(min(vals.values()), abs=1e-6)
