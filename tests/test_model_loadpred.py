"""Checkpoint round-trip: reload the saved model and check test MAE < 0.2
(parity: reference tests/test_model_loadpred.py:18-57)."""

import json
import os

import numpy as np

import hydragnn_tpu
from test_graphs import _generate_data


def test_model_loadpred():
    with open(os.path.join(os.path.dirname(__file__), "inputs", "ci.json")) as f:
        config = json.load(f)
    config["NeuralNetwork"]["Architecture"]["model_type"] = "SAGE"
    _generate_data(config)

    hydragnn_tpu.run_training(config)
    # run_prediction rebuilds the model from scratch and loads the .pk
    error, tasks_error, true_values, predicted_values = (
        hydragnn_tpu.run_prediction(config))
    for ihead in range(len(true_values)):
        mae = float(np.abs(
            np.asarray(true_values[ihead]) -
            np.asarray(predicted_values[ihead])).mean())
        assert mae < 0.2, f"Head {ihead} MAE {mae} >= 0.2 after reload"


def test_state_roundtrip(tmp_path):
    """save_state/load_state preserve every leaf exactly."""
    import jax
    import jax.numpy as jnp

    from hydragnn_tpu.graph.batch import GraphSample, HeadSpec, PadSpec, collate
    from hydragnn_tpu.graph.neighborlist import radius_graph
    from hydragnn_tpu.models.base import GraphHeadCfg, ModelConfig
    from hydragnn_tpu.models.create import create_model
    from hydragnn_tpu.train.optimizer import select_optimizer
    from hydragnn_tpu.train.trainer import (
        create_train_state,
        load_state,
        make_train_step,
        save_state,
    )

    rng = np.random.RandomState(0)
    samples = []
    for _ in range(4):
        pos = rng.rand(6, 3).astype(np.float32) * 2
        samples.append(GraphSample(
            x=rng.rand(6, 1), pos=pos,
            edge_index=radius_graph(pos, 1.0, 8),
            graph_y=rng.rand(1), node_y=rng.rand(6, 1)))
    batch = collate(samples, PadSpec.for_batch(4, 6, 30),
                    [HeadSpec("e", "graph", 1)])
    cfg = ModelConfig(
        model_type="GIN", input_dim=1, hidden_dim=8, output_dim=(1,),
        output_type=("graph",), graph_head=GraphHeadCfg(1, 8, 1, (8,)),
        node_head=None, task_weights=(1.0,), num_conv_layers=2)
    model = create_model(cfg)
    opt = select_optimizer({"type": "AdamW", "learning_rate": 1e-3})
    state = create_train_state(model, batch, opt)
    step = jax.jit(make_train_step(model, cfg, opt))
    state, _ = step(state, batch)

    save_state(state, "roundtrip", str(tmp_path))
    restored = load_state(state, "roundtrip", str(tmp_path))
    for a, b in zip(jax.tree.leaves(state), jax.tree.leaves(restored)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))


def test_full_state_resume(tmp_path, monkeypatch):
    """Training.full_state_checkpoint writes orbax full-state epochs;
    Training.continue restores it (step counter included) through
    run_training — the step-level-resume capability the reference lacks."""
    import jax

    monkeypatch.chdir(tmp_path)
    with open(os.path.join(os.path.dirname(__file__), "inputs", "ci.json")) as f:
        config = json.load(f)
    config["NeuralNetwork"]["Architecture"]["model_type"] = "GIN"
    config["NeuralNetwork"]["Training"]["num_epoch"] = 2
    config["NeuralNetwork"]["Training"]["full_state_checkpoint"] = 1
    _generate_data(config, num_samples_tot=60)

    state1, _, _ = hydragnn_tpu.run_training(
        config, logs_dir=str(tmp_path / "logs"))
    step1 = int(state1.step)
    assert step1 > 0
    # the orbax dir must exist — otherwise `continue` silently falls back to
    # the pickle (which also carries step) and this test asserts nothing
    from hydragnn_tpu.config.config import get_log_name_config
    from hydragnn_tpu.utils.checkpoint import latest_step

    orbax_dir = str(tmp_path / "logs" / get_log_name_config(config) / "orbax")
    assert latest_step(orbax_dir) is not None, "orbax checkpoint not written"

    config["NeuralNetwork"]["Training"]["continue"] = 1
    state2, _, _ = hydragnn_tpu.run_training(
        config, logs_dir=str(tmp_path / "logs"))
    # resumed run starts from the restored step counter, not zero
    assert int(state2.step) > step1
    leaves1 = jax.tree.leaves(state1.params)
    leaves2 = jax.tree.leaves(state2.params)
    assert any(
        not np.array_equal(np.asarray(a), np.asarray(b))
        for a, b in zip(leaves1, leaves2)), "continued run did not train"
