"""Smoke matrix over activation x loss types (parity: reference
tests/test_loss_and_activation_functions.py:20-23, interface-only)."""

import json
import os

import pytest

import hydragnn_tpu
from test_graphs import _generate_data


@pytest.mark.parametrize(
    "activation", ["relu", "selu", "prelu", "elu", "lrelu_025"])
@pytest.mark.parametrize("loss", ["mse", "mae", "smooth_l1", "rmse"])
def test_loss_and_activation_functions(activation, loss):
    with open(os.path.join(os.path.dirname(__file__), "inputs", "ci.json")) as f:
        config = json.load(f)
    config["NeuralNetwork"]["Architecture"]["model_type"] = "SAGE"
    config["NeuralNetwork"]["Architecture"]["activation_function"] = activation
    config["NeuralNetwork"]["Training"]["loss_function_type"] = loss
    config["NeuralNetwork"]["Training"]["num_epoch"] = 2
    _generate_data(config, num_samples_tot=60)
    hydragnn_tpu.run_training(config)
