"""Fault-tolerant replica fleet (docs/SERVING.md "Replica fleet"):
power-of-two-choices routing balance, failover retry on replica death
under the request deadline (the chaos-kill acceptance: zero 5xx while a
replica is SIGKILLed and auto-restarted), breaker-driven ejection +
readmission, crash restart with exponential backoff and the
restart-storm cap, graceful drain-and-replace with zero drops, rolling
fleet reload with first-replica rollback, fleet-aggregated
/healthz + /metrics (drain-rate EWMA sum as the autoscaling signal),
minimum-surviving-replica Retry-After propagation, and the
HYDRAGNN_CHAOS_REPLICA_* knob parsing.

Tier-1 budget discipline: ONE tiny SAGE engine with ONE bucket is
compiled once for the whole module; every replica is an
``engine.fork()`` sharing that compile cache, so fleets (and replica
restarts) cost milliseconds.
"""

import json
import pickle
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from hydragnn_tpu.graph.batch import GraphSample, HeadSpec, PadSpec, collate
from hydragnn_tpu.graph.neighborlist import radius_graph
from hydragnn_tpu.models.base import GraphHeadCfg, ModelConfig
from hydragnn_tpu.models.create import create_model
from hydragnn_tpu.resilience import FleetChaos, ServeChaos
from hydragnn_tpu.serve import (
    FleetRouter,
    FleetSupervisor,
    InProcessReplica,
    InferenceEngine,
    InferenceState,
    ServingConfig,
)
from hydragnn_tpu.serve.batcher import RequestShedError
from hydragnn_tpu.serve.fleet import ReplicaDeadError
from hydragnn_tpu.serve.router import FleetSaturatedError


def _sample(n=6, seed=0):
    rng = np.random.RandomState(seed)
    pos = rng.rand(n, 3).astype(np.float32) * 2.0
    return GraphSample(x=rng.rand(n, 1).astype(np.float32), pos=pos,
                       edge_index=radius_graph(pos, 1.2, 8))


_HEADS = [HeadSpec("energy", "graph", 1)]


@pytest.fixture(scope="module")
def engine():
    """One tiny SAGE engine, ONE bucket, compiled once for the module;
    all fleet replicas fork it (shared executable cache)."""
    import jax

    cfg = ModelConfig(
        model_type="SAGE", input_dim=1, hidden_dim=8, output_dim=(1,),
        output_type=("graph",), graph_head=GraphHeadCfg(1, 8, 1, (8,)),
        node_head=None, task_weights=(1.0,), num_conv_layers=2)
    model = create_model(cfg)
    pads = [PadSpec.for_batch(4, 16, 64)]
    example = collate([_sample()], pads[0], _HEADS)
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        example, train=False)
    state = InferenceState(step=0, params=variables["params"],
                           batch_stats=variables.get("batch_stats", {}))
    eng = InferenceEngine(cfg, state, _HEADS, pads)
    eng.warmup()
    return eng


class _Tel:
    """Recording telemetry stub for the SUPERVISOR (replicas use the
    disabled MetricsLogger): keeps the (kind, fields) stream so tests
    can assert on event reasons, not just counts."""

    def __init__(self):
        self.events = []
        self._lock = threading.Lock()

    def health(self, kind, **fields):
        with self._lock:
            self.events.append((kind, fields))

    @property
    def health_counts(self):
        with self._lock:
            out = {}
            for k, _ in self.events:
                out[k] = out.get(k, 0) + 1
            return out

    def kinds(self, kind):
        with self._lock:
            return [f for k, f in self.events if k == kind]


def _mk_router(engine, n=3, fleet_chaos=None, chaos_factories=None,
               start=True, **overrides):
    kw = dict(port=0, max_wait_ms=2, request_deadline_ms=10_000.0,
              breaker_threshold=2, breaker_cooldown_s=0.25,
              predict_timeout_s=5.0, fleet_probe_s=0.03,
              fleet_restart_backoff_s=0.05,
              fleet_restart_backoff_max_s=0.4, fleet_max_restarts=6,
              fleet_restart_window_s=30.0, fleet_drain_timeout_s=5.0)
    kw.update(overrides)
    serving = ServingConfig(**kw)
    tel = _Tel()
    cf = chaos_factories or {}
    from hydragnn_tpu.telemetry import MetricsLogger

    replicas = [
        InProcessReplica(i, engine.fork, serving,
                         MetricsLogger.disabled(),
                         chaos_factory=cf.get(i))
        for i in range(n)
    ]
    fleet = FleetSupervisor(replicas, serving, telemetry=tel,
                            chaos=fleet_chaos)
    router = FleetRouter(fleet, serving=serving, cfg=engine.cfg,
                         telemetry=tel)
    if start:
        router.start()
    return router


def _wait_until(cond, timeout=10.0, step=0.01):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if cond():
            return True
        time.sleep(step)
    return False


def _post(port, path, obj, timeout=30.0, headers=None):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _get(port, path, timeout=10.0):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
        return json.loads(r.read())


def _sample_json(s, **extra):
    return {"x": s.x.tolist(), "pos": s.pos.tolist(),
            "edge_index": s.edge_index.tolist(), **extra}


# ---------------------------------------------------------------------------
# Routing + aggregation
# ---------------------------------------------------------------------------


def test_routing_balance_and_aggregated_metrics(engine):
    """po2 least-outstanding routing spreads 200s across ALL replicas,
    and /healthz + /metrics aggregate per-replica state, breaker
    snapshots, restart counts, fleet totals, and the drain-rate EWMA sum
    (the autoscaling signal)."""
    router = _mk_router(engine, n=3)
    try:
        for i in range(30):
            code, out = _post(router.port, "/predict",
                              _sample_json(_sample(5, seed=i)))
            assert code == 200
            assert len(out["heads"]["energy"]) == 1
            assert out["replica"] in (0, 1, 2)
        h = _get(router.port, "/healthz")
        assert h["status"] == "ok"
        assert h["live"] == h["total"] == 3
        assert h["quorum"] == 2 and not h["below_quorum"]
        assert [r["state"] for r in h["replicas"]] == ["live"] * 3
        m = _get(router.port, "/metrics")
        per = m["router"]["per_replica_200"]
        # po2 over 3 replicas gives each ~1/3 of 30 requests; a replica
        # with ZERO dispatches means routing is broken, not unlucky
        # (P(zero) ~ 5e-6)
        assert set(per) == {"0", "1", "2"}
        assert all(v > 0 for v in per.values())
        assert sum(per.values()) == m["router"]["responses_200"] == 30
        fl = m["fleet"]
        assert fl["live"] == fl["total"] == 3
        assert fl["by_state"] == {"live": 3}
        assert len(fl["replicas"]) == 3
        for s in fl["replicas"]:
            assert s["breaker"]["state"] == "closed"
            assert s["restarts"] == 0
        # the autoscaling signal: sum of per-replica drain-rate EWMAs,
        # positive once flushes have run
        assert m["autoscale"]["signal"] == "drain_rate_rps_sum"
        assert m["autoscale"]["value"] > 0
        assert m["fleet"]["drain_rate_rps_sum"] == m["autoscale"]["value"]
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# Failover: replica death under load (the chaos-kill acceptance)
# ---------------------------------------------------------------------------


def test_chaos_kill_zero_5xx_and_auto_restart(engine):
    """With 3 replicas serving concurrent load, a hard kill of one
    (the SIGKILL analog: in-flight work FAILS, no drain) yields ZERO
    non-200 responses — in-flight requests are retried on another
    replica within their deadline — and the supervisor restarts and
    re-admits the dead replica automatically."""
    router = _mk_router(engine, n=3)
    fleet = router.fleet
    results, errors = [], []
    lock = threading.Lock()

    def client(wid):
        for i in range(8):
            try:
                code, out = _post(router.port, "/predict",
                                  _sample_json(_sample(5, seed=wid * 31 + i),
                                               timeout_ms=10_000))
                with lock:
                    results.append(code)
            except urllib.error.HTTPError as e:
                with lock:
                    errors.append(e.code)
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(repr(e))

    try:
        threads = [threading.Thread(target=client, args=(w,))
                   for w in range(4)]
        for t in threads:
            t.start()
        time.sleep(0.05)
        victim = fleet.replicas[1]
        victim.kill()  # SIGKILL analog: no drain, in-flight fails
        for t in threads:
            t.join(timeout=60)
        assert not errors, errors
        assert len(results) == 32 and all(c == 200 for c in results)
        # the supervisor restarts and re-admits the victim
        assert _wait_until(lambda: victim.state == "live", timeout=10)
        assert victim.restarts == 1
        counts = router.telemetry.health_counts
        assert counts.get("replica_dead", 0) >= 1
        assert counts.get("replica_restart", 0) >= 1
        # and it serves again
        assert _wait_until(
            lambda: _post(router.port, "/predict",
                          _sample_json(_sample(6, seed=99)))[0] == 200,
            timeout=5)
    finally:
        router.shutdown()


def test_in_flight_failover_is_deterministic(engine):
    """Unit-level failover: a replica that dies UNDER a request (its
    predict raises ReplicaDeadError) is marked dead and the request is
    answered by a DIFFERENT replica — one retry, same budget."""
    router = _mk_router(engine, n=2)
    fleet = router.fleet
    try:
        r0 = fleet.replicas[0]

        def dead_predict(req, deadline_s):
            raise ReplicaDeadError("simulated mid-request death")

        r0.predict = dead_predict
        req = router.build_request(_sample_json(_sample(5, seed=7)))
        # every request lands on replica 1 eventually, whatever po2 picks
        for _ in range(4):
            out = router.route_predict(req, deadline_s=10.0)
            assert out["replica"] == 1
        assert r0.state in ("dead", "restarting", "live")
        m = router.metrics()
        assert m["router"]["failovers"] >= 1
        assert router.telemetry.health_counts.get("fleet_retry", 0) >= 1
    finally:
        router.shutdown()


def test_fleet_chaos_kill_via_probe_ticks(engine):
    """The HYDRAGNN_CHAOS_REPLICA_KILL path end-to-end: the supervisor
    consults FleetChaos each probe tick, kills the armed replica, and
    the fleet recovers on its own while requests keep flowing."""
    chaos = FleetChaos(kill=[(2, False, 1)])  # kill replica 1 at tick 2
    router = _mk_router(engine, n=3, fleet_chaos=chaos)
    fleet = router.fleet
    try:
        assert _wait_until(lambda: chaos.injected["kill"] == 1, timeout=5)
        assert _wait_until(
            lambda: fleet.replicas[1].restarts == 1
            and fleet.replicas[1].state == "live", timeout=10)
        for i in range(6):
            code, _ = _post(router.port, "/predict",
                            _sample_json(_sample(5, seed=40 + i)))
            assert code == 200
        counts = router.telemetry.health_counts
        assert counts.get("replica_dead", 0) >= 1
        assert counts.get("replica_restart", 0) >= 1
        dead = router.telemetry.kinds("replica_dead")
        assert any(f.get("reason") == "chaos_kill" for f in dead)
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# Breaker-driven ejection + readmission
# ---------------------------------------------------------------------------


def test_breaker_ejection_and_readmission(engine):
    """A replica whose predict path persistently fails trips ITS OWN
    breaker: the router fails over (clients see 200s, never 5xx), the
    supervisor ejects the replica from routing, and once the cooldown
    elapses it is readmitted — the next routed flush is the half-open
    probe, which (chaos now disarmed) closes the breaker."""
    # replica 0's first 3 flushes raise; breaker threshold 2 trips it
    router = _mk_router(
        engine, n=2,
        chaos_factories={0: lambda: ServeChaos(fail_steps={1, 2, 3})})
    fleet = router.fleet
    r0 = fleet.replicas[0]
    try:
        # keep offering load until replica 0 has failed enough to eject
        def pump(i):
            code, _ = _post(router.port, "/predict",
                            _sample_json(_sample(5, seed=60 + i),
                                         timeout_ms=10_000))
            assert code == 200

        i = 0
        while r0.state != "ejected" and i < 200:
            pump(i)
            i += 1
        assert r0.state == "ejected", \
            f"never ejected after {i} requests ({r0.breaker.snapshot()})"
        assert router.telemetry.health_counts.get("replica_eject", 0) >= 1
        # readmission after the cooldown; the half-open probe flush may
        # burn the last chaos failure, so keep pumping until it closes
        assert _wait_until(
            lambda: r0.state in ("live", "ejected"), timeout=5)
        deadline = time.monotonic() + 20
        while time.monotonic() < deadline:
            pump(i)
            i += 1
            if r0.state == "live" and r0.breaker.state == "closed" \
                    and r0.chaos.inner.injected_failures >= 3:
                break
        assert r0.breaker.state == "closed"
        assert r0.state == "live"
        assert router.telemetry.health_counts.get("replica_readmit", 0) >= 1
        assert r0.chaos.inner.injected_failures == 3
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# Restart backoff + storm cap
# ---------------------------------------------------------------------------


def test_restart_backoff_and_storm_cap(engine):
    """Each crash doubles the restart backoff; more than
    fleet_max_restarts restarts inside the window marks the replica
    FAILED (no more restart attempts — a crash loop must not burn the
    fleet's attention forever) while the rest keep serving."""
    router = _mk_router(engine, n=2, fleet_max_restarts=2,
                        fleet_restart_backoff_s=0.05,
                        fleet_restart_backoff_max_s=0.2)
    fleet = router.fleet
    r1 = fleet.replicas[1]
    try:
        for k in range(1, 3):
            r1.kill()
            assert _wait_until(
                lambda: r1.state == "live" and r1.restarts == k,
                timeout=10), f"restart {k} never happened"
        # backoff grew beyond the base across consecutive crashes
        assert fleet._backoff[r1.idx] > fleet._base_backoff
        # third crash exceeds the cap (2 restarts already in window)
        r1.kill()
        assert _wait_until(lambda: r1.state == "failed", timeout=10)
        ejects = router.telemetry.kinds("replica_eject")
        assert any(f.get("reason") == "restart_storm" for f in ejects)
        # no further restarts, and the fleet keeps serving on replica 0
        assert r1.restarts == 2
        code, _ = _post(router.port, "/predict",
                        _sample_json(_sample(5, seed=77)))
        assert code == 200
        h = _get(router.port, "/healthz")
        assert h["status"] == "degraded" and h["live"] == 1
        # below majority quorum (1 < 2) -> the teleview WARNING signal
        assert h["below_quorum"]
        assert router.telemetry.health_counts.get("fleet_degraded", 0) >= 1
    finally:
        router.shutdown()


def test_fleet_empty_503_only_when_no_replica_remains(engine):
    """503 is reserved for a truly EMPTY fleet: with restarts disabled
    (fleet_max_restarts=0) and every replica killed, /predict answers
    503 + Retry-After and /healthz reports status empty."""
    router = _mk_router(engine, n=2, fleet_max_restarts=0)
    fleet = router.fleet
    try:
        for r in fleet.replicas:
            r.kill()
        assert _wait_until(
            lambda: all(r.state == "failed" for r in fleet.replicas),
            timeout=10)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(router.port, "/predict", _sample_json(_sample(5, seed=3)))
        assert ei.value.code == 503
        assert int(ei.value.headers["Retry-After"]) >= 1
        assert json.loads(ei.value.read())["fleet"] == "empty"
        assert _get(router.port, "/healthz")["status"] == "empty"
        assert router.metrics()["router"]["empty_503"] == 1
        assert router.telemetry.health_counts.get("fleet_empty", 0) >= 1
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# Graceful drain-and-replace
# ---------------------------------------------------------------------------


def test_drain_and_replace_zero_drop(engine):
    """drain_and_replace recycles a live replica with ZERO dropped
    requests: routing stops first, in-flight work finishes, the batcher
    drains, and a fresh incarnation rejoins."""
    router = _mk_router(engine, n=2)
    fleet = router.fleet
    results, errors = [], []
    lock = threading.Lock()
    stop = threading.Event()

    def client():
        i = 0
        while not stop.is_set():
            try:
                code, _ = _post(router.port, "/predict",
                                _sample_json(_sample(5, seed=200 + i)))
                with lock:
                    results.append(code)
            except Exception as e:  # noqa: BLE001
                with lock:
                    errors.append(repr(e))
            i += 1

    try:
        t = threading.Thread(target=client)
        t.start()
        time.sleep(0.05)
        assert fleet.drain_and_replace(0) is True
        time.sleep(0.05)
        stop.set()
        t.join(timeout=30)
        assert not errors, errors
        assert results and all(c == 200 for c in results)
        r0 = fleet.replicas[0]
        assert r0.state == "live" and r0.restarts == 1
        counts = router.telemetry.health_counts
        assert counts.get("replica_drain", 0) == 1
        assert counts.get("replica_restart", 0) >= 1
        # a non-live replica refuses the drain (no double recycle)
        r0.state = "ejected"
        assert fleet.drain_and_replace(0) is False
        r0.state = "live"
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# Rolling fleet reload
# ---------------------------------------------------------------------------


def test_rolling_reload_and_first_replica_rollback(engine, tmp_path):
    """POST /reload fans the PR 5 hot-reload out one replica at a time:
    a good candidate swaps into EVERY replica (bit-identical answers);
    a corrupt candidate is rejected BY THE FIRST replica (409
    rolled_back) without touching the rest, and the fleet keeps
    serving."""
    import jax

    router = _mk_router(engine, n=2)
    fleet = router.fleet
    try:
        s0 = _sample(6, seed=80)
        code, base = _post(router.port, "/predict", _sample_json(s0))
        assert code == 200

        r0 = fleet.replicas[0]
        copy_params = jax.tree_util.tree_map(np.asarray,
                                             r0.engine.state.params)
        copy_stats = jax.tree_util.tree_map(np.asarray,
                                            r0.engine.state.batch_stats)
        ck = tmp_path / "cand.pk"
        with open(ck, "wb") as f:  # graftlint: disable=ROB002 (test fixture in tmp dir; crash durability irrelevant)
            pickle.dump({"step": 21, "params": copy_params,
                         "batch_stats": copy_stats}, f)
        code, out = _post(router.port, "/reload", {"checkpoint": str(ck)})
        assert code == 200 and out["status"] == "ok"
        assert out["replicas"] == 2 and out["step"] == 21
        for r in fleet.replicas:
            assert r.engine.reload_stats()["reloads"] == 1
            assert r.state == "live"
        # same weights -> bit-identical across the rolling swap
        code, after = _post(router.port, "/predict", _sample_json(s0))
        assert code == 200 and after["heads"] == base["heads"]
        counts = router.telemetry.health_counts
        assert counts.get("rolling_reload_start", 0) == 1
        assert counts.get("rolling_reload_ok", 0) == 1

        # corrupt candidate: NaN params fail the FIRST replica's golden
        # replay -> 409 rolled_back, the rest untouched
        bad = ServeChaos(reload_corrupt=1).on_reload_state(
            InferenceState(step=22, params=copy_params,
                           batch_stats=copy_stats))
        bad_ck = tmp_path / "bad.pk"
        with open(bad_ck, "wb") as f:  # graftlint: disable=ROB002 (test fixture in tmp dir; crash durability irrelevant)
            pickle.dump({"step": 22, "params": bad.params,
                         "batch_stats": bad.batch_stats}, f)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(router.port, "/reload", {"checkpoint": str(bad_ck)})
        assert ei.value.code == 409
        assert json.loads(ei.value.read())["status"] == "rolled_back"
        # exactly one replica saw (and rejected) the candidate; nobody
        # swapped, nobody left rotation
        fails = [r.engine.reload_stats()["reload_failures"]
                 for r in fleet.replicas]
        assert sorted(fails) == [0, 1]
        assert all(r.engine.reload_stats()["reloads"] == 1
                   for r in fleet.replicas)
        assert all(r.state == "live" for r in fleet.replicas)
        rb = router.telemetry.kinds("rolling_reload_rollback")
        assert len(rb) == 1 and rb[0]["swapped"] == 0
        code, after = _post(router.port, "/predict", _sample_json(s0))
        assert code == 200 and after["heads"] == base["heads"]
        # 404 for a missing checkpoint, fleet untouched
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(router.port, "/reload",
                  {"checkpoint": str(tmp_path / "no.pk")})
        assert ei.value.code == 404

        # version-skew guard: a replica that CRASHES after the rolling
        # reload restarts from the ORIGINAL weights — the supervisor
        # must re-reload it onto the fleet's desired checkpoint before
        # it takes traffic (no silent mixed-version fleet)
        r1 = fleet.replicas[1]
        r1.kill()
        assert _wait_until(
            lambda: r1.state == "live" and r1.restarts == 1
            and int(np.asarray(r1.engine.state.step)) == 21, timeout=10), \
            (r1.state, r1.restarts, int(np.asarray(r1.engine.state.step)))
        assert r1.engine.reload_stats()["reloads"] == 1  # fresh fork, synced
        code, after = _post(router.port, "/predict", _sample_json(s0))
        assert code == 200 and after["heads"] == base["heads"]
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# Retry-After propagation (satellite: min across surviving replicas)
# ---------------------------------------------------------------------------


def test_retry_after_is_min_across_surviving_replicas(engine):
    """When the router retries and ultimately sheds, the client's
    Retry-After is the MINIMUM surviving-replica drain estimate — the
    soonest ANY replica expects capacity — not whichever replica was
    asked first."""
    router = _mk_router(engine, n=3)
    fleet = router.fleet
    try:
        estimates = {0: 7.0, 1: 3.0, 2: 5.0}
        for r in fleet.replicas:
            est = estimates[r.idx]

            def shed(req, deadline_s, _est=est):
                raise RequestShedError("backlog exceeds deadline",
                                       retry_after_s=_est)

            r.predict = shed
        req = router.build_request(_sample_json(_sample(5, seed=5)))
        with pytest.raises(FleetSaturatedError) as ei:
            router.route_predict(req, deadline_s=30.0)
        assert ei.value.retry_after_s == 3.0
        # and over HTTP: 429 whose Retry-After is ceil(min estimate)
        with pytest.raises(urllib.error.HTTPError) as http_ei:
            _post(router.port, "/predict",
                  _sample_json(_sample(5, seed=6), timeout_ms=30_000))
        assert http_ei.value.code == 429
        assert int(http_ei.value.headers["Retry-After"]) == 3
        assert router.metrics()["router"]["saturated_429"] >= 2
    finally:
        router.shutdown()


def test_router_429_both_deadline_spellings(engine):
    """PR 5's two 429 spellings hold at the ROUTER layer too: a zero
    budget via the timeout_ms body field and via the X-Timeout-Ms
    header both shed with 429 + Retry-After, and a sane deadline is
    served."""
    router = _mk_router(engine, n=2)
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(router.port, "/predict",
                  _sample_json(_sample(5, seed=50), timeout_ms=0))
        assert ei.value.code == 429
        assert int(ei.value.headers["Retry-After"]) >= 1
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(router.port, "/predict",
                  _sample_json(_sample(5, seed=51)),
                  headers={"X-Timeout-Ms": "0"})
        assert ei.value.code == 429
        assert int(ei.value.headers["Retry-After"]) >= 1
        code, out = _post(router.port, "/predict",
                          _sample_json(_sample(5, seed=52),
                                       timeout_ms=10_000))
        assert code == 200 and len(out["heads"]["energy"]) == 1
        # negative budget is a client error at the router too
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(router.port, "/predict",
                  _sample_json(_sample(5, seed=53), timeout_ms=-5))
        assert ei.value.code == 400
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# Chaos knob parsing + fleet config knobs
# ---------------------------------------------------------------------------


def test_fleet_chaos_env_parsing(monkeypatch):
    assert FleetChaos.from_env() is None  # nothing armed
    monkeypatch.setenv("HYDRAGNN_CHAOS_REPLICA_KILL", "3:1")
    monkeypatch.setenv("HYDRAGNN_CHAOS_REPLICA_HANG", "5")
    monkeypatch.setenv("HYDRAGNN_CHAOS_REPLICA_FLAP", "2+")
    c = FleetChaos.from_env()
    assert c.kill == [(3, False, 1)]
    assert c.hang == [(5, False, None)]
    assert c.flap == [(2, True, None)]
    # tick semantics: nothing at 1; flap from 2 on; pinned kill at 3
    assert c.on_probe() == []
    assert c.on_probe() == [("flap", None)]
    assert c.on_probe() == [("kill", 1), ("flap", None)]
    assert c.on_probe() == [("flap", None)]
    assert c.injected == {"kill": 1, "hang": 0, "flap": 3,
                          "tenant_hot": 0, "scale_fail": 0}
    # config-dict spelling, env wins
    monkeypatch.delenv("HYDRAGNN_CHAOS_REPLICA_HANG")
    monkeypatch.delenv("HYDRAGNN_CHAOS_REPLICA_FLAP")
    c = FleetChaos.from_env({"kill": "9", "hang": "4,6"})
    assert c.kill == [(3, False, 1)]  # env beats the config dict
    assert c.hang == [(4, False, None), (6, False, None)]


def test_fleet_config_knobs_and_env(monkeypatch):
    d = ServingConfig()
    assert d.fleet_replicas == 0 and d.fleet_probe_s > 0
    with pytest.raises(ValueError):
        ServingConfig(fleet_replicas=-1)
    with pytest.raises(ValueError):
        ServingConfig(fleet_probe_s=0)
    with pytest.raises(ValueError):
        ServingConfig(fleet_restart_backoff_s=-1)
    with pytest.raises(ValueError):
        ServingConfig(fleet_replicas=2, fleet_quorum=3)
    monkeypatch.setenv("HYDRAGNN_SERVE_FLEET", "3")
    monkeypatch.setenv("HYDRAGNN_SERVE_FLEET_INPROCESS", "1")
    monkeypatch.setenv("HYDRAGNN_SERVE_FLEET_PROBE_S", "0.5")
    monkeypatch.setenv("HYDRAGNN_SERVE_FLEET_BACKOFF_S", "0.25")
    monkeypatch.setenv("HYDRAGNN_SERVE_FLEET_MAX_RESTARTS", "7")
    monkeypatch.setenv("HYDRAGNN_SERVE_FLEET_QUORUM", "2")
    cfg = ServingConfig.from_section({"fleet_replicas": 9,
                                      "fleet_probe_s": 9.0})
    assert cfg.fleet_replicas == 3  # env wins over config
    assert cfg.fleet_inprocess is True
    assert cfg.fleet_probe_s == 0.5
    assert cfg.fleet_restart_backoff_s == 0.25
    assert cfg.fleet_max_restarts == 7
    assert cfg.fleet_quorum == 2
    from hydragnn_tpu.serve import serving_defaults

    for key in ("fleet_replicas", "fleet_inprocess", "fleet_probe_s",
                "fleet_restart_backoff_s", "fleet_restart_backoff_max_s",
                "fleet_max_restarts", "fleet_restart_window_s",
                "fleet_drain_timeout_s", "fleet_startup_timeout_s",
                "fleet_quorum"):
        assert key in serving_defaults()


def test_engine_fork_shares_compile_cache(engine):
    """fork() is what makes in-process fleets affordable: the fork
    serves identical answers through the SHARED compiled executables
    (zero new compiles) while owning its own reload machinery."""
    before = engine.cache_stats()["warmup_compiles"]
    fork = engine.fork()
    assert fork._compiled is engine._compiled
    fork.warmup()  # cache-hits every bucket
    assert engine.cache_stats()["warmup_compiles"] == before
    assert fork.cache_stats()["misses"] == 0
    s = _sample(7, seed=90)
    np.testing.assert_array_equal(
        engine.predict_samples([s])[0]["energy"],
        fork.predict_samples([s])[0]["energy"])
    # independent reload state: rolling back the fork never touches the
    # parent
    assert fork.reload_stats()["reloads"] == 0
    assert fork.rollback() is False
