"""Worker proving the JSON-config entry is multi-host-launchable AS
DOCUMENTED (docs/SCALING.md): no jax.distributed glue here — only the
launcher-style env (JAX_NUM_PROCESSES/JAX_PROCESS_ID, the same role
OMPI_COMM_WORLD_*/SLURM_* play under mpirun/srun).  ``run_training`` itself
must call setup_distributed() (parity: reference run_training calls
setup_ddp internally, hydragnn/run_training.py:77)."""

import json
import os
import sys

rank = int(sys.argv[1])
world = int(sys.argv[2])
port = sys.argv[3]
scratch = sys.argv[4]

os.environ["JAX_PLATFORMS"] = "cpu"
# launcher env only — the entry point must bootstrap from these
os.environ["JAX_NUM_PROCESSES"] = str(world)
os.environ["JAX_PROCESS_ID"] = str(rank)
os.environ["HYDRAGNN_MASTER_PORT"] = port

import jax

jax.config.update("jax_platforms", "cpu")
# NOTE: no backend-touching call may happen before run_training —
# jax.distributed.initialize must precede any XLA backend init

tests_dir = os.path.dirname(os.path.abspath(__file__))
sys.path.insert(0, os.path.dirname(tests_dir))
sys.path.insert(0, tests_dir)
os.chdir(scratch)
os.environ["SERIALIZED_DATA_PATH"] = scratch

import numpy as np  # noqa: E402

import hydragnn_tpu  # noqa: E402

with open(os.path.join(tests_dir, "inputs", "ci.json")) as f:
    config = json.load(f)
config["NeuralNetwork"]["Architecture"]["model_type"] = "GIN"
config["NeuralNetwork"]["Training"]["num_epoch"] = 4
config["Verbosity"]["level"] = 0

if rank == 0:
    from ci_data import generate_cached

    for name, path in config["Dataset"]["path"].items():
        generate_cached(name, path, 120 if name == "train" else 30)
    # data-ready marker: the barrier below needs the distributed runtime,
    # which run_training hasn't set up yet — use the filesystem
    open(os.path.join(scratch, ".data_ready"), "w").close()
else:
    import time

    while not os.path.exists(os.path.join(scratch, ".data_ready")):
        time.sleep(0.1)

state, history, fconfig = hydragnn_tpu.run_training(config)

assert jax.process_count() == world, "run_training did not bootstrap"

import hashlib  # noqa: E402

h = hashlib.sha256()
for leaf in jax.tree.leaves(jax.device_get(state.params)):
    h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())

print(f"MPRESULT rank={rank} val={history['val'][-1]:.8f} "
      f"params={h.hexdigest()[:16]}")
