"""Flight recorder (docs/TELEMETRY.md "Tracing"): trace-identity
adoption, the bounded lock-guarded span ring, end-to-end serve spans
(request -> linked flush -> queue-wait/pad/predict children), trace ids
on shed/timeout answers and across failover, the train-phase wrappers,
the comm-vs-compute A/B probe, the SLO burn-rate monitor, and the
PR-15-style default-off purity claims."""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from hydragnn_tpu.graph.batch import GraphSample, HeadSpec, PadSpec, collate
from hydragnn_tpu.graph.neighborlist import radius_graph
from hydragnn_tpu.models.base import GraphHeadCfg, ModelConfig
from hydragnn_tpu.models.create import create_model
from hydragnn_tpu.serve import (
    InferenceEngine,
    InferenceServer,
    InferenceState,
    ServingConfig,
)
from hydragnn_tpu.telemetry import MetricsLogger, TelemetryConfig
from hydragnn_tpu.telemetry.slo import BurnRateMonitor, SloConfig, tail_jsonl
from hydragnn_tpu.telemetry.trace import (
    SpanRecorder,
    chrome_trace,
    extract_trace_context,
    quantile,
)


def _sample(n=6, seed=0):
    rng = np.random.RandomState(seed)
    pos = rng.rand(n, 3).astype(np.float32) * 2.0
    return GraphSample(x=rng.rand(n, 1).astype(np.float32), pos=pos,
                       edge_index=radius_graph(pos, 1.2, 8))


_HEADS = [HeadSpec("energy", "graph", 1)]


@pytest.fixture(scope="module")
def _engine_mod():
    """ONE tiny SAGE engine for the whole module — each HTTP test
    reassigns `engine.telemetry` before building its server (the
    batcher inherits it at construction); the `engine` wrapper
    restores it after."""
    import jax

    cfg = ModelConfig(
        model_type="SAGE", input_dim=1, hidden_dim=8, output_dim=(1,),
        output_type=("graph",), graph_head=GraphHeadCfg(1, 8, 1, (8,)),
        node_head=None, task_weights=(1.0,), num_conv_layers=2)
    model = create_model(cfg)
    pads = [PadSpec.for_batch(2, 16, 64)]
    example = collate([_sample()], pads[0], _HEADS)
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        example, train=False)
    state = InferenceState(step=0, params=variables["params"],
                           batch_stats=variables.get("batch_stats", {}))
    eng = InferenceEngine(cfg, state, _HEADS, pads,
                          serving=ServingConfig(max_wait_ms=10),
                          telemetry=None)
    eng.warmup()
    return eng


@pytest.fixture
def engine(_engine_mod):
    prev = _engine_mod.telemetry
    yield _engine_mod
    _engine_mod.telemetry = prev


def _traced_logger(tmp_path=None, sinks=()):
    """Enabled MetricsLogger with the flight recorder armed; JSONL sink
    only when a directory is given (ring-only otherwise)."""
    return MetricsLogger(
        TelemetryConfig(enable=True, trace=True, trace_ring=512,
                        sinks=tuple(sinks)),
        run_name="trace_test",
        out_dir=str(tmp_path) if tmp_path is not None else None)


def _post(port, obj, headers=None, timeout=30.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}/predict", data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json", **(headers or {})})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read()), dict(r.headers)


def _sample_json(s, **extra):
    return {"x": s.x.tolist(), "pos": s.pos.tolist(),
            "edge_index": s.edge_index.tolist(), **extra}


# ---------------------------------------------------------------------------
# Trace identity: adopt-or-mint precedence, malformed values ignored
# ---------------------------------------------------------------------------


def test_extract_trace_context_precedence_and_malformed():
    tid, pid = "ab" * 16, "cd" * 8
    # traceparent wins (and carries the parent span id)
    ctx = extract_trace_context(
        {"traceparent": f"00-{tid}-{pid}-01", "X-Request-Id": "other"})
    assert (ctx.trace_id, ctx.parent_id, ctx.minted) == (tid, pid, False)
    # X-Request-Id next: arbitrary token schemes are adopted verbatim
    ctx = extract_trace_context({"X-Request-Id": "req_1:retry-2.a"})
    assert ctx.trace_id == "req_1:retry-2.a" and not ctx.minted
    # body-field spelling when no header is present
    ctx = extract_trace_context({}, {"trace_id": "bench-0-7"})
    assert ctx.trace_id == "bench-0-7" and not ctx.minted
    # malformed traceparent falls through to X-Request-Id, silently
    ctx = extract_trace_context(
        {"traceparent": "00-zznothex-01", "X-Request-Id": "fallback"})
    assert ctx.trace_id == "fallback" and not ctx.minted
    # header-splitting / oversize / non-string ids are treated as absent
    for bad in ("a b", "x\r\nSet-Cookie: no", "q" * 129, ""):
        ctx = extract_trace_context({"X-Request-Id": bad})
        assert ctx.minted and len(ctx.trace_id) == 32
    ctx = extract_trace_context({}, {"trace_id": 123})
    assert ctx.minted
    # minted ids are W3C-width and unique
    a, b = extract_trace_context({}), extract_trace_context({})
    assert a.trace_id != b.trace_id
    assert "-01" in a.traceparent() and a.trace_id in a.traceparent()


def test_quantile_nearest_rank():
    assert quantile([], 0.99) == 0.0
    vals = sorted(float(v) for v in range(1, 101))
    assert quantile(vals, 0.50) == 51.0
    assert quantile(vals, 0.99) == 100.0
    assert quantile([7.0], 0.99) == 7.0


# ---------------------------------------------------------------------------
# SpanRecorder: bounded ring, thread safety, percentiles, chrome export
# ---------------------------------------------------------------------------


def test_span_ring_bounded_overwrites_oldest():
    rec = SpanRecorder(ring=8)
    for i in range(50):
        rec.record_interval("serve.predict", 0.0, 0.001, seq=i)
    snap = rec.snapshot()
    assert len(snap) == 8  # bounded, whatever the request count
    assert [r["seq"] for r in snap] == list(range(42, 50))  # oldest-first
    pct = rec.percentiles()["serve.predict"]
    assert pct["count"] == 50  # lifetime count survives the overwrite
    assert pct["p50_ms"] == pytest.approx(1.0, rel=0.01)
    # the per-name reservoir is bounded too (no unbounded growth)
    assert len(rec._durations["serve.predict"]) <= 8
    assert rec.summary()["recorded"] == 50


def test_span_ring_lock_guarded_under_concurrent_writers():
    emitted = []
    rec = SpanRecorder(ring=64, emit=emitted.append)
    n_threads, per_thread = 8, 200

    def writer(wid):
        for i in range(per_thread):
            with rec.span("serve.request", trace_id=f"t{wid}-{i}"):
                pass
            rec.record_interval("serve.queue_wait", 0.0, 0.0005)

    threads = [threading.Thread(target=writer, args=(w,))
               for w in range(n_threads)]
    for t in threads:
        t.start()
    for t in threads:
        t.join()
    total = n_threads * per_thread
    pct = rec.percentiles()
    assert pct["serve.request"]["count"] == total
    assert pct["serve.queue_wait"]["count"] == total
    assert rec.summary()["recorded"] == 2 * total
    assert len(rec.snapshot()) == 64
    assert len(emitted) == 2 * total  # every span reached the JSONL hook


def test_span_context_manager_and_chrome_export():
    rec = SpanRecorder(ring=16)
    with rec.span("serve.flush", trace_id="tr1", bucket=4):
        time.sleep(0.002)
    rec.record_interval("train.step", 1.0, 1.5, trace_id="run",
                        parent_id="abcd")
    doc = chrome_trace(rec.snapshot() + [{"event": "step"}])  # non-spans skipped
    evs = doc["traceEvents"]
    assert len(evs) == 2
    flush = next(e for e in evs if e["name"] == "serve.flush")
    assert flush["ph"] == "X" and flush["pid"] == "serve"
    assert flush["dur"] >= 2000  # microseconds
    assert flush["args"]["bucket"] == 4 and flush["args"]["trace_id"] == "tr1"
    step = next(e for e in evs if e["name"] == "train.step")
    assert step["pid"] == "train" and step["dur"] == pytest.approx(5e5)
    assert step["args"]["parent_id"] == "abcd"


# ---------------------------------------------------------------------------
# End-to-end serve: request span + linked flush + phase children in JSONL
# ---------------------------------------------------------------------------


def test_server_traces_end_to_end(tmp_path, engine):
    tel = _traced_logger(tmp_path, sinks=("jsonl",))
    engine.telemetry = tel  # before the server: the batcher inherits it
    srv = InferenceServer(engine,
                          serving=ServingConfig(port=0, max_wait_ms=5))
    srv.start()
    rids = [f"e2e-{i}" for i in range(4)]
    try:
        for rid in rids:
            code, out, hdrs = _post(
                srv.port, _sample_json(_sample(5, seed=int(rid[-1]))),
                headers={"X-Request-Id": rid})
            assert code == 200
            assert out["trace_id"] == rid  # body echo
            assert hdrs.get("X-Request-Id") == rid  # header echo
        with urllib.request.urlopen(
                f"http://127.0.0.1:{srv.port}/metrics", timeout=10) as r:
            m = json.loads(r.read())
        # /metrics span-latency breakdown: queue-wait vs predict
        assert m["spans"]["serve.request"]["count"] >= 4
        assert m["spans"]["serve.queue_wait"]["count"] >= 4
        assert m["spans"]["serve.predict"]["p99_ms"] >= 0.0
    finally:
        srv.shutdown()
        tel.finalize()
    recs = [json.loads(line)
            for line in open(tel.jsonl_path) if line.strip()]
    spans = [r for r in recs if r.get("event") == "span"]
    by_name = {}
    for s in spans:
        by_name.setdefault(s["name"], []).append(s)
    # one request span per stamped id, status attached
    req_ids = {s["trace_id"] for s in by_name["serve.request"]}
    assert set(rids) <= req_ids
    assert all(s["status"] == 200 and s["dur_ms"] >= 0.0
               for s in by_name["serve.request"])
    # every traced request is linked from some flush span, and the flush
    # has pad/predict children parented to its span_id on its trace
    linked = {t for s in by_name["serve.flush"] for t in s.get("links", [])}
    assert set(rids) <= linked
    for flush in by_name["serve.flush"]:
        kids = [s for s in spans
                if s.get("parent_id") == flush["span_id"]]
        assert {k["name"] for k in kids} >= {"serve.pad", "serve.predict"}
    # queue-wait rides the REQUEST's trace (client id resolves the story)
    qw_ids = {s["trace_id"] for s in by_name["serve.queue_wait"]}
    assert set(rids) <= qw_ids
    # the manifest carries the span summary block
    manifest = next(r for r in recs if r.get("event") == "manifest")
    assert manifest["spans"]["recorded"] >= len(spans)
    assert "serve.request" in manifest["spans"]["by_name"]


def test_shed_and_timeout_answers_carry_trace_id(engine):
    tel = _traced_logger()
    engine.telemetry = tel
    srv = InferenceServer(engine,
                          serving=ServingConfig(port=0, max_wait_ms=5))
    srv.start()
    try:
        # warm the drain-rate estimate so admission control can shed
        code, _, _ = _post(srv.port, _sample_json(_sample(5, seed=1)),
                           headers={"X-Request-Id": "warm-1"})
        assert code == 200
        # an impossible deadline -> 429, and the answer must quote the id
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(srv.port,
                  _sample_json(_sample(5, seed=2), timeout_ms=0.001),
                  headers={"X-Request-Id": "shed-me"})
        assert ei.value.code == 429
        body = json.loads(ei.value.read())
        assert body["trace_id"] == "shed-me"
        assert ei.value.headers.get("X-Request-Id") == "shed-me"
        # malformed body: the id was adopted from the HEADERS before the
        # body read, so even a 400 quotes it
        req = urllib.request.Request(
            f"http://127.0.0.1:{srv.port}/predict", data=b"not json",
            headers={"Content-Type": "application/json",
                     "X-Request-Id": "bad-body"})
        with pytest.raises(urllib.error.HTTPError) as ei:
            urllib.request.urlopen(req, timeout=10)
        assert ei.value.code == 400
        assert json.loads(ei.value.read())["trace_id"] == "bad-body"
        # error request spans land in the ring with their status
        statuses = {}
        for s in tel.spans.snapshot():
            if s["name"] == "serve.request":
                statuses[s["trace_id"]] = s["status"]
        assert statuses.get("shed-me") == 429
        assert statuses.get("bad-body") == 400
    finally:
        srv.shutdown()


def test_predict_timeout_504_carries_trace_id(engine):
    from hydragnn_tpu.resilience import ServeChaos

    engine.telemetry = _traced_logger()
    srv = InferenceServer(
        engine,
        serving=ServingConfig(port=0, max_wait_ms=0, predict_timeout_s=0.05,
                              breaker_threshold=0),  # breaker off: raw 504
        chaos=ServeChaos(predict_ms=400.0, lat_from=1))
    srv.start()
    try:
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(srv.port, _sample_json(_sample(5, seed=3)),
                  headers={"X-Request-Id": "slow-one"})
        assert ei.value.code == 504
        assert json.loads(ei.value.read())["trace_id"] == "slow-one"
        assert ei.value.headers.get("X-Request-Id") == "slow-one"
    finally:
        srv.shutdown()


def test_trace_id_survives_midflight_failover(engine):
    """The PR-8 chaos path: replica 0 dies UNDER the request; the router
    retries on replica 1 and the answer still quotes the client's id —
    and the fleet-edge request span records the whole story as ONE
    trace."""
    from hydragnn_tpu.serve import (
        FleetRouter,
        FleetSupervisor,
        InProcessReplica,
    )
    from hydragnn_tpu.serve.fleet import ReplicaDeadError

    eng = engine
    serving = ServingConfig(port=0, max_wait_ms=2,
                            request_deadline_ms=10_000.0,
                            fleet_probe_s=0.03,
                            fleet_restart_backoff_s=0.05)
    tel = _traced_logger()
    replicas = [InProcessReplica(i, eng.fork, serving,
                                 MetricsLogger.disabled())
                for i in range(2)]
    fleet = FleetSupervisor(replicas, serving, telemetry=tel)
    router = FleetRouter(fleet, serving=serving, cfg=eng.cfg, telemetry=tel)
    router.start()
    try:
        def dead_predict(req, deadline_s):
            raise ReplicaDeadError("simulated mid-request death")

        fleet.replicas[0].predict = dead_predict
        for i in range(4):  # whatever po2 picks first, all must fail over
            rid = f"failover-{i}"
            code, out, hdrs = _post(
                router.port, _sample_json(_sample(5, seed=i),
                                          timeout_ms=10_000),
                headers={"X-Request-Id": rid})
            assert code == 200
            assert out["replica"] == 1
            assert out["trace_id"] == rid
            assert hdrs.get("X-Request-Id") == rid
        assert router.metrics()["router"]["failovers"] >= 1
        spans = {s["trace_id"]: s for s in tel.spans.snapshot()
                 if s["name"] == "serve.request"}
        for i in range(4):
            assert spans[f"failover-{i}"]["status"] == 200
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# Train-step phase attribution
# ---------------------------------------------------------------------------


def test_traced_loader_and_step_record_phases():
    import jax.numpy as jnp

    from hydragnn_tpu.train.trainer import _traced_loader, _traced_step

    rec = SpanRecorder(ring=32)
    batches = list(range(3))
    seen = list(_traced_loader(iter(batches), rec))
    assert seen == batches  # pass-through, order preserved

    def step_fn(state, g):
        return state + g, {"loss": jnp.float32(g)}

    stepped = _traced_step(step_fn, rec)
    state = 0
    for g in seen:
        state, metrics = stepped(state, g)
    assert state == 3 and float(metrics["loss"]) == 2.0
    pct = rec.percentiles()
    assert pct["train.data_wait"]["count"] == 3
    assert pct["train.h2d"]["count"] == 3
    assert pct["train.step"]["count"] == 3


# ---------------------------------------------------------------------------
# Comm-vs-compute A/B probe (forced 8-device CPU mesh via conftest)
# ---------------------------------------------------------------------------


@pytest.fixture(scope="module")
def mesh_harness():
    import jax

    from test_resilience import _batch, _model

    from hydragnn_tpu.parallel.mesh import (
        make_mesh,
        replicate_state,
        stack_batches,
    )
    from hydragnn_tpu.train.optimizer import select_optimizer
    from hydragnn_tpu.train.trainer import create_train_state

    cfg, model = _model()
    opt = select_optimizer({"type": "AdamW", "learning_rate": 1e-3})
    mesh = make_mesh()
    n_dev = len(jax.devices())
    batches = stack_batches([_batch(seed=i) for i in range(n_dev)])
    state = replicate_state(create_train_state(model, _batch(), opt), mesh)
    return cfg, model, opt, mesh, state, batches


def test_comm_probe_default_off_hlo_pure(mesh_harness):
    """PR-15-style purity: default-off lowers the SAME program, and the
    probe annotation changes compiled-HLO METADATA only — the lowered
    StableHLO is byte-identical, so the timed program IS the production
    program."""
    from hydragnn_tpu.parallel.mesh import make_dp_train_step

    cfg, model, opt, mesh, state, batches = mesh_harness
    base_l = make_dp_train_step(model, cfg, opt, mesh).lower(state, batches)
    off_l = make_dp_train_step(model, cfg, opt, mesh, comm_probe=False
                               ).lower(state, batches)
    on_l = make_dp_train_step(model, cfg, opt, mesh, comm_probe=True
                              ).lower(state, batches)
    base_txt = base_l.as_text()
    assert off_l.as_text() == base_txt
    assert on_l.as_text() == base_txt  # annotation is metadata-only
    assert "comm.dp_psum" not in base_txt
    # the compiled program carries the region names as op metadata — the
    # xprof/Perfetto attribution handle
    compiled_on = on_l.compile().as_text()
    assert "comm.dp_psum" in compiled_on
    assert "comm.dp_psum" not in base_l.compile().as_text()


def test_dp_comms_probe_reports_split_and_preserves_state(mesh_harness):
    import jax
    import jax.numpy as jnp

    from hydragnn_tpu.telemetry.comms import comm_split, dp_comms_probe

    cfg, model, opt, mesh, state, batches = mesh_harness
    out = dp_comms_probe(model, cfg, opt, mesh, state, batches, iters=1)
    assert out["path"] == "dp"
    assert out["n_devices"] == len(jax.devices())
    assert out["comm_ms"] >= 0.0 and out["compute_ms"] >= 0.0
    assert out["step_ms"] == pytest.approx(
        out["comm_ms"] + out["compute_ms"], abs=0.01)
    assert 0.0 <= out["comm_pct"] <= 100.0
    assert "comm.dp_psum_ms" in out["parts"]
    assert "upper bound" in out["method"]
    # the probe timed COPIES: the caller's state was never donated
    leaf = jax.tree.leaves(state.params)[0]
    assert bool(jnp.isfinite(jnp.sum(leaf)))

    # split arithmetic clamps: comm can never exceed the step
    s = comm_split(2.0, 5.0)
    assert s == {"step_ms": 2.0, "comm_ms": 2.0, "compute_ms": 0.0,
                 "comm_pct": 100.0}


def test_log_comms_lands_in_manifest(tmp_path):
    tel = _traced_logger(tmp_path, sinks=("jsonl",))
    tel.log_comms({"path": "dp", "step_ms": 4.0, "comm_ms": 1.0,
                   "compute_ms": 3.0, "comm_pct": 25.0})
    tel.finalize()
    recs = [json.loads(line)
            for line in open(tel.jsonl_path) if line.strip()]
    assert any(r.get("event") == "comms" and r["path"] == "dp"
               for r in recs)
    manifest = next(r for r in recs if r.get("event") == "manifest")
    assert manifest["comms"]["comm_pct"] == 25.0


# ---------------------------------------------------------------------------
# SLO burn-rate monitor
# ---------------------------------------------------------------------------


class _Tel:
    def __init__(self):
        self.events = []

    def health(self, kind, **fields):
        self.events.append((kind, fields))


def test_slo_monitor_fires_on_synthetic_burn_edge_triggered():
    tel = _Tel()
    mon = BurnRateMonitor(
        SloConfig(shed_budget=0.05, window_s=60.0, burn=2.0),
        telemetry=tel)
    # 10 accepted answers, then a shed storm: 5/15 = 33% >> 2x5% = 10%
    for i in range(10):
        mon.observe({"event": "step", "source": "serve", "num_graphs": 1,
                     "predict_ms": 5.0, "wait_ms": 1.0}, now=float(i))
    assert mon.check(now=10.0) is None  # compliant so far
    for i in range(5):
        mon.observe({"event": "health", "kind": "request_shed"},
                    now=10.0 + i)
    v = mon.check(now=15.0)
    assert v is not None and v["budget"] == "shed_ratio"
    assert v["shed"] == 5 and v["accepted"] == 10
    assert [k for k, _ in tel.events] == ["slo_burn"]
    # edge-triggered: the SAME excursion stays quiet
    assert mon.check(now=16.0) is None
    assert mon.fired == 1
    # a compliant window re-arms (sheds age out), a fresh burn re-fires
    assert mon.check(now=200.0) is None
    for i in range(5):
        mon.observe({"event": "health", "kind": "queue_full"},
                    now=300.0 + i)
    mon.observe({"event": "step", "source": "serve", "num_graphs": 1,
                 "predict_ms": 5.0, "wait_ms": 1.0}, now=305.0)
    assert mon.check(now=306.0) is not None
    assert mon.fired == 2


def test_slo_monitor_latency_budget_uses_request_spans():
    tel = _Tel()
    mon = BurnRateMonitor(
        SloConfig(p99_ms=100.0, shed_budget=1.0, window_s=60.0),
        telemetry=tel)
    for i in range(20):
        mon.observe({"event": "span", "name": "serve.request",
                     "dur_ms": 250.0}, now=float(i))
    v = mon.check(now=21.0)
    assert v is not None and v["budget"] == "latency_p99"
    assert v["p99_ms"] == 250.0 and v["target_ms"] == 100.0
    assert tel.events[0][0] == "slo_burn"


def test_slo_monitor_quiet_on_compliant_stream():
    tel = _Tel()
    mon = BurnRateMonitor(
        SloConfig(p99_ms=1000.0, shed_budget=0.05, window_s=60.0),
        telemetry=tel)
    for i in range(100):
        mon.observe({"event": "step", "source": "serve", "num_graphs": 4,
                     "predict_ms": 3.0, "wait_ms": 2.0}, now=float(i))
        assert mon.check(now=float(i)) is None
    # one shed among 400 accepted: well under budget
    mon.observe({"event": "health", "kind": "request_shed"}, now=100.0)
    assert mon.check(now=101.0) is None
    assert mon.fired == 0 and tel.events == []


def test_slo_tail_jsonl_offline_replay(tmp_path):
    burn = tmp_path / "burn.jsonl"
    with open(burn, "w") as f:
        for i in range(10):
            f.write(json.dumps({"event": "step", "source": "serve",
                                "num_graphs": 1, "predict_ms": 1.0,
                                "wait_ms": 0.0, "t": float(i)}) + "\n")
        f.write("not json — skipped, not fatal\n")
        for i in range(10):
            f.write(json.dumps({"event": "health", "kind": "queue_full",
                                "t": 10.0 + i}) + "\n")
    cfg = SloConfig(shed_budget=0.05, window_s=60.0, burn=2.0)
    mon, violations = tail_jsonl(str(burn), cfg)
    assert len(violations) == 1  # edge-triggered: one per excursion
    assert violations[0]["budget"] == "shed_ratio"
    assert mon.fired == 1

    quiet = tmp_path / "quiet.jsonl"
    with open(quiet, "w") as f:
        for i in range(50):
            f.write(json.dumps({"event": "step", "source": "serve",
                                "num_graphs": 2, "predict_ms": 1.0,
                                "wait_ms": 0.0, "t": float(i)}) + "\n")
    mon, violations = tail_jsonl(str(quiet), cfg)
    assert violations == [] and mon.fired == 0


def test_slo_config_env_overrides(monkeypatch):
    monkeypatch.setenv("HYDRAGNN_SLO_P99_MS", "250")
    monkeypatch.setenv("HYDRAGNN_SLO_SHED_BUDGET", "0.02")
    monkeypatch.setenv("HYDRAGNN_SLO_WINDOW_S", "30")
    monkeypatch.setenv("HYDRAGNN_SLO_BURN", "4.0")
    cfg = SloConfig(p99_ms=1.0, shed_budget=0.5, window_s=5.0, burn=1.0)
    assert (cfg.p99_ms, cfg.shed_budget, cfg.window_s, cfg.burn) \
        == (250.0, 0.02, 30.0, 4.0)
    monkeypatch.setenv("HYDRAGNN_SLO_BURN", "not-a-float")
    assert SloConfig(burn=3.0).burn == 3.0  # malformed env falls back
