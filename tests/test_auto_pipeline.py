"""Auto fast-pipeline selection (_auto_pipeline): the out-of-the-box
run_training turns on scan chunking + device residency exactly when it is
safe — single process, known loader lengths, enough dispatch units for
drop_last to be harmless, staged corpus within the HBM budget — and the
explicit env knobs always win (round-4 VERDICT item 7)."""

import numpy as np

from hydragnn_tpu.train.trainer import _auto_pipeline


class _FakeLoader:
    def __init__(self, n, batch_bytes=1 << 20):
        self.n = n
        self.batch = np.zeros(batch_bytes // 4, np.float32)

    def __len__(self):
        return self.n

    def __iter__(self):
        return iter([self.batch] * self.n)


class _NoLenLoader:
    def __iter__(self):
        return iter([])


def test_small_dataset_stays_off():
    k, res = _auto_pipeline(_FakeLoader(6), _FakeLoader(1), _FakeLoader(1))
    assert (k, res) == (1, False)


def test_medium_dataset_scans_without_residency():
    # 16 batches: scan on (waste-aware pick: 16 divides evenly),
    # residency off (< 32 batches)
    k, res = _auto_pipeline(_FakeLoader(16), _FakeLoader(2), _FakeLoader(2))
    assert k == 16
    assert res is False


def test_k_prefers_low_waste():
    # 33 units: K=32 would drop 1/33 (allowed, <= 1/8) -> picks 32;
    # 20 units: K=20 divides exactly -> picks 20
    k, _ = _auto_pipeline(_FakeLoader(33), _FakeLoader(2), _FakeLoader(2))
    assert k == 32
    k, _ = _auto_pipeline(_FakeLoader(20), _FakeLoader(2), _FakeLoader(2))
    assert k == 20


def test_large_dataset_gets_both():
    k, res = _auto_pipeline(_FakeLoader(128), _FakeLoader(8), _FakeLoader(8))
    assert k == 32
    assert res is True


def test_budget_bounds_residency(monkeypatch):
    monkeypatch.setenv("HYDRAGNN_RESIDENT_BUDGET_MB", "10")
    # 128 batches x 1 MiB > 10 MiB budget -> no residency, scan still on
    k, res = _auto_pipeline(_FakeLoader(128), _FakeLoader(8), _FakeLoader(8))
    assert k == 32
    assert res is False


def test_stack_factor_prevents_zero_step_epochs():
    # 11 raw batches over 8 devices = 1 dispatch unit: far below the
    # 8-unit floor, so K must stay 1 (the exact regression the
    # full-state-resume test caught: K=2 left a zero-step epoch)
    k, res = _auto_pipeline(
        _FakeLoader(11), _FakeLoader(3), _FakeLoader(3), stack_factor=8)
    assert (k, res) == (1, False)


def test_unknown_length_stays_off():
    k, res = _auto_pipeline(_NoLenLoader(), _NoLenLoader(), _NoLenLoader())
    assert (k, res) == (1, False)


def test_kill_switch(monkeypatch):
    monkeypatch.setenv("HYDRAGNN_AUTO_PIPELINE", "0")
    k, res = _auto_pipeline(_FakeLoader(128), _FakeLoader(8), _FakeLoader(8))
    assert (k, res) == (1, False)
