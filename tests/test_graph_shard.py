"""Graph sharding across the 8-device CPU mesh (docs/SCALING.md §6).

Two backends behind ``Training.graph_shard``:

- **halo** (production, graph/partition.py + mesh.py:make_halo_train_step):
  locality-aware node partition, L-hop halo exchanged per step through one
  bounded all_to_all, per-device residency N/D + halo.  Tested for
  partition bit-exactness, forward/grad/train parity vs single device,
  the VJP reduce-scatter contract on input cotangents, residency + the
  no-full-[N,F]-buffer HLO assertion, ZeRO compose, knobs, and the
  trainer e2e path (telemetry + teleview + resume).
- **gspmd** (correctness baseline, parallel/graph_shard.py): exact
  numerics, full-array all-gathers, zero memory headroom — the original
  three tests kept as the baseline's contract.
"""

import json
import os

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh, NamedSharding, PartitionSpec as P

from hydragnn_tpu.graph.batch import GraphSample, HeadSpec, PadSpec, collate
from hydragnn_tpu.graph.neighborlist import radius_graph
from hydragnn_tpu.graph.partition import (
    GraphShardConfig,
    apply_plan,
    build_shard_plan,
    check_graph_shard_backend,
    graph_shard_training_defaults,
    shard_batch_halo,
)
from hydragnn_tpu.models.base import GraphHeadCfg, ModelConfig, NodeHeadCfg
from hydragnn_tpu.models.create import create_model
from hydragnn_tpu.parallel.graph_shard import (
    make_sharded_forward,
    shard_batch,
)
from hydragnn_tpu.parallel.mesh import (
    make_halo_eval_step,
    make_halo_train_step,
    make_mesh,
    replicate_state,
)
from hydragnn_tpu.train.optimizer import select_optimizer
from hydragnn_tpu.train.trainer import (
    create_train_state,
    make_eval_step,
    make_train_step,
)

N_DEV = 8


def _mesh():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-device CPU mesh (conftest sets XLA_FLAGS)")
    return Mesh(np.array(devs[:8]), ("data",))


def _batch_and_model(model_type="SAGE", n_graphs=8, npg=16):
    rng = np.random.RandomState(0)
    samples = []
    for _ in range(n_graphs):
        pos = rng.rand(npg, 3).astype(np.float32) * 3.0
        x = rng.rand(npg, 1).astype(np.float32)
        ei = radius_graph(pos, radius=1.5, max_neighbours=8)
        samples.append(GraphSample(
            x=x, pos=pos, edge_index=ei,
            graph_y=rng.rand(1).astype(np.float32), node_y=x))
    # node/edge dims divisible by 8 so they shard; graph dim deliberately
    # NOT divisible so the replicate-when-indivisible fallback is exercised
    max_e = max(s.num_edges for s in samples)
    pad = PadSpec(num_nodes=n_graphs * npg + 8,
                  num_edges=-(-(n_graphs * max_e + 1) // 8) * 8,
                  num_graphs=n_graphs + 9)
    heads = [HeadSpec("energy", "graph", 1), HeadSpec("charge", "node", 1)]
    batch = collate(samples, pad, heads)

    cfg = ModelConfig(
        model_type=model_type, input_dim=1, hidden_dim=16,
        output_dim=(1, 1), output_type=("graph", "node"),
        graph_head=GraphHeadCfg(1, 16, 1, (16,)),
        node_head=NodeHeadCfg(num_headlayers=1, dim_headlayers=(16,),
                              type="mlp"),
        task_weights=(1.0, 1.0), num_conv_layers=2,
        pna_avg_deg_log=1.2, pna_avg_deg_lin=3.0,
        num_gaussians=8, num_filters=16, radius=1.5, max_neighbours=8)
    model = create_model(cfg)
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        batch, train=False)
    return model, variables, batch


def _giant_batch(n1=200, n2=40, seed=0):
    """One big + one small graph, both spanning shards when partitioned."""
    rng = np.random.RandomState(seed)
    samples = []
    for n in (n1, n2):
        pos = rng.rand(n, 3).astype(np.float32) * (n ** (1 / 3.0))
        x = rng.rand(n, 1).astype(np.float32)
        ei = radius_graph(pos, radius=0.9, max_neighbours=12)
        samples.append(GraphSample(
            x=x, pos=pos, edge_index=ei,
            graph_y=rng.rand(1).astype(np.float32), node_y=x * 2.0))
    tot_e = sum(s.num_edges for s in samples)
    pad = PadSpec(num_nodes=n1 + n2 + 8, num_edges=tot_e + 8, num_graphs=3)
    heads = [HeadSpec("energy", "graph", 1), HeadSpec("charge", "node", 1)]
    return collate(samples, pad, heads), heads


def _halo_model(hidden=16):
    cfg = ModelConfig(
        model_type="SAGE", input_dim=1, hidden_dim=hidden,
        output_dim=(1, 1), output_type=("graph", "node"),
        graph_head=GraphHeadCfg(1, hidden, 1, (hidden,)),
        node_head=NodeHeadCfg(1, (hidden,), "mlp"),
        task_weights=(1.0, 1.0), num_conv_layers=2)
    return cfg, create_model(cfg)


# ---------------------------------------------------------------------------
# gspmd baseline (the original contract: exact numerics, no memory claim)
# ---------------------------------------------------------------------------


def test_sharded_batch_is_actually_sharded():
    mesh = _mesh()
    _, _, batch = _batch_and_model()
    sb = shard_batch(batch, mesh)
    shards = sb.x.addressable_shards
    assert len(shards) == 8
    assert shards[0].data.shape[0] == batch.x.shape[0] // 8
    # the graph dim (17) doesn't divide 8 -> graph arrays stay REPLICATED
    assert batch.graph_mask.shape[0] % 8 != 0
    gshards = sb.graph_mask.addressable_shards
    assert all(s.data.shape == batch.graph_mask.shape for s in gshards)


@pytest.mark.parametrize("model_type", ["SAGE", "GIN", "PNA", "SchNet"])
def test_sharded_forward_matches_single_device(model_type):
    mesh = _mesh()
    model, variables, batch = _batch_and_model(model_type)
    want = model.apply(variables, batch, train=False)

    fwd = make_sharded_forward(model, mesh)
    got = fwd(variables, shard_batch(batch, mesh))
    for w, g in zip(want, got):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-5, atol=2e-5)


def test_sharded_grad_matches_single_device():
    mesh = _mesh()
    model, variables, batch = _batch_and_model("SAGE")

    def loss(variables, b):
        out = model.apply(variables, b, train=False)
        return (jnp.sum((out[0] * b.graph_mask[:, None]) ** 2)
                + jnp.sum((out[1] * b.node_mask[:, None]) ** 2))

    g_want = jax.grad(loss)(variables, batch)
    repl = NamedSharding(mesh, P())
    g_got = jax.jit(jax.grad(loss), in_shardings=(repl, None),
                    out_shardings=repl)(variables, shard_batch(batch, mesh))
    flat_w, _ = jax.tree_util.tree_flatten(g_want)
    flat_g, _ = jax.tree_util.tree_flatten(g_got)
    for w, g in zip(flat_w, flat_g):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-4, atol=2e-4)


# ---------------------------------------------------------------------------
# halo backend: partition plan bit-exactness
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("method", ["block", "bfs", "sfc"])
def test_partition_plan_roundtrip(method):
    """Every real node is owned by exactly one shard, every real edge by
    exactly its receiver's shard, halo slots resolve to real remote nodes,
    and the reported stats are internally consistent — pure indexing, so
    asserted bit-exactly."""
    batch, heads = _giant_batch()
    n_real = int(np.asarray(batch.node_mask).sum())
    e_real = int(np.asarray(batch.edge_mask).sum())
    plan = build_shard_plan(batch, N_DEV, method=method, hops=2)
    # nodes: disjoint cover
    ids = plan.local_ids[plan.local_ids >= 0]
    assert sorted(ids.tolist()) == list(range(n_real))
    # owned edges: disjoint cover of the real edges
    owned = plan.edge_ids[plan.edge_owned > 0]
    assert sorted(owned.tolist()) == list(range(e_real))
    # halo slots point at real nodes owned by the slot's peer shard
    for d in range(plan.n_shards):
        for p in range(plan.n_shards):
            base = p * plan.halo_pair
            sel = plan.halo_ids[d, base:base + plan.halo_pair]
            sel = sel[sel >= 0]
            assert np.isin(sel, plan.local_ids[p]).all()
    # every shard's edges index inside the extended row space
    assert (plan.senders < plan.ext_n).all() and (plan.senders >= 0).all()
    assert (plan.receivers < plan.ext_n).all()
    s = plan.stats
    assert s["n_shards"] == N_DEV and s["method"] == method
    assert 0.0 <= s["cut_edge_pct"] <= 100.0
    assert s["halo_rows_max"] <= N_DEV * plan.halo_pair
    # the locality claim itself: bfs/sfc must beat the naive block order
    if method in ("bfs", "sfc"):
        blk = build_shard_plan(batch, N_DEV, method="block", hops=2)
        assert s["cut_edge_pct"] < blk.stats["cut_edge_pct"]


def test_partition_nondivisible_and_empty_halo():
    """Non-divisible real-node counts pad the last shard; D disconnected
    components in block order produce a ZERO-cut partition whose halo is
    empty (halo_pair stays >= 1 so the all_to_all shape is never
    zero-sized)."""
    rng = np.random.RandomState(3)
    # 61 real nodes (not divisible by 8), one blob
    pos = rng.rand(61, 3).astype(np.float32) * 2.0
    ei = radius_graph(pos, radius=0.8, max_neighbours=8)
    s = GraphSample(x=rng.rand(61, 1).astype(np.float32), pos=pos,
                    edge_index=ei, graph_y=np.ones(1, np.float32))
    batch = collate([s], PadSpec(num_nodes=72, num_edges=ei.shape[1] + 8,
                                 num_graphs=2),
                    [HeadSpec("e", "graph", 1)])
    plan = build_shard_plan(batch, N_DEV, method="sfc", hops=2)
    ids = plan.local_ids[plan.local_ids >= 0]
    assert ids.size == 61
    assert sorted(ids.tolist()) == list(range(61))

    # fewer real nodes than shards (a degenerate tail val batch): trailing
    # shards end up empty instead of killing the run mid-validation
    s5 = GraphSample(x=rng.rand(5, 1).astype(np.float32),
                     pos=rng.rand(5, 3).astype(np.float32),
                     edge_index=np.asarray([[0, 1, 2, 3], [1, 2, 3, 4]]),
                     graph_y=np.ones(1, np.float32))
    b5 = collate([s5], PadSpec(num_nodes=8, num_edges=8, num_graphs=2),
                 [HeadSpec("e", "graph", 1)])
    p5 = build_shard_plan(b5, N_DEV, method="block", hops=2)
    ids5 = p5.local_ids[p5.local_ids >= 0]
    assert sorted(ids5.tolist()) == list(range(5))
    assert (p5.local_ids[5:] < 0).all()  # empty trailing shards

    # 8 disconnected 8-node cliques, block order -> shard == component
    comps = []
    for c in range(N_DEV):
        cpos = rng.rand(8, 3).astype(np.float32) * 0.3 + 10.0 * c
        cei = radius_graph(cpos, radius=1.0, max_neighbours=8)
        comps.append((cpos, cei))
    xs = np.concatenate([np.full((8, 1), i, np.float32)
                         for i in range(N_DEV)])
    poss = np.concatenate([c[0] for c in comps])
    eis = np.concatenate(
        [c[1] + 8 * i for i, c in enumerate(comps)], axis=1)
    s2 = GraphSample(x=xs, pos=poss, edge_index=eis,
                     graph_y=np.ones(1, np.float32))
    b2 = collate([s2], PadSpec(num_nodes=72, num_edges=eis.shape[1] + 8,
                               num_graphs=2), [HeadSpec("e", "graph", 1)])
    p2 = build_shard_plan(b2, N_DEV, method="block", hops=2)
    assert p2.stats["cut_edge_pct"] == 0.0
    assert p2.stats["halo_rows_max"] == 0
    assert p2.halo_pair >= 1  # never a zero-sized collective
    assert (p2.halo_ids < 0).all()
    # the zero-halo partition still trains: one step, loss finite
    mesh = _mesh()
    cfg = ModelConfig(
        model_type="SAGE", input_dim=1, hidden_dim=8, output_dim=(1,),
        output_type=("graph",), graph_head=GraphHeadCfg(1, 8, 1, (8,)),
        node_head=None, task_weights=(1.0,), num_conv_layers=2)
    model = create_model(cfg)
    hb = apply_plan(b2, p2, ["graph"])
    opt = select_optimizer({"type": "AdamW", "learning_rate": 0.01})
    state = replicate_state(create_train_state(model, b2, opt), mesh)
    step = make_halo_train_step(model, cfg, opt, mesh)
    _, m = step(state, hb)
    assert np.isfinite(float(m["loss"]))


# ---------------------------------------------------------------------------
# halo backend: forward / grad / train parity vs single device
# ---------------------------------------------------------------------------


def test_halo_forward_and_loss_parity():
    """Eval loss through the halo step equals the single-device eval loss
    (the halo-context psums reassemble the exact global masked means), and
    per-shard node outputs match the single-device rows — BatchNorm,
    pooling and both head types exercised."""
    mesh = _mesh()
    batch, heads = _giant_batch()
    cfg, model = _halo_model()
    hb, plan = shard_batch_halo(batch, N_DEV, method="sfc", hops=2,
                                head_types=[h.type for h in heads])
    opt = select_optimizer({"type": "AdamW", "learning_rate": 0.01})
    state = create_train_state(model, batch, opt, seed=0)

    m1 = jax.jit(make_eval_step(model, cfg))(state, batch)
    mh = make_halo_eval_step(model, cfg, mesh)(
        replicate_state(state, mesh), hb)
    np.testing.assert_allclose(float(mh["loss"]), float(m1["loss"]),
                               rtol=1e-6)
    for a, b in zip(m1["per_head"], mh["per_head"]):
        np.testing.assert_allclose(float(b), float(a), rtol=1e-6)

    # node outputs row-for-row (local rows only; halo rows are scratch)
    single = np.asarray(m1["outputs"][1])
    shard_out = np.asarray(mh["outputs"][1])  # [D, ext_n, 1]
    for d in range(N_DEV):
        ids = plan.local_ids[d]
        ok = ids >= 0
        np.testing.assert_allclose(
            shard_out[d][:plan.n_local][ok], single[ids[ok]],
            rtol=1e-5, atol=1e-6)


def test_halo_input_cotangents_reduce_scatter():
    """The backward contract: d(loss)/d(x_local) through the halo step —
    whose VJP runs the inverse all_to_all and scatter-adds halo cotangents
    onto owner rows — equals the single-device d(loss)/d(x) sliced to each
    shard's local rows.  This is the reduce-scatter the tentpole names,
    asserted end-to-end."""
    mesh = _mesh()
    batch, heads = _giant_batch()
    cfg, model = _halo_model()
    hb, plan = shard_batch_halo(batch, N_DEV, method="bfs", hops=2,
                                head_types=[h.type for h in heads])
    opt = select_optimizer({"type": "AdamW", "learning_rate": 0.01})
    state = create_train_state(model, batch, opt, seed=0)
    s_repl = replicate_state(state, mesh)

    ev1 = make_eval_step(model, cfg)
    evh = make_halo_eval_step(model, cfg, mesh)

    g_single = jax.grad(
        lambda xs: ev1(state, batch.replace(x=xs))["loss"])(batch.x)
    g_halo = jax.grad(
        lambda xs: evh(s_repl, hb.replace(x=xs))["loss"])(hb.x)
    g_single = np.asarray(g_single)
    g_halo = np.asarray(jax.device_get(g_halo))  # [D, n_local, F]
    for d in range(N_DEV):
        ids = plan.local_ids[d]
        ok = ids >= 0
        np.testing.assert_allclose(
            g_halo[d][ok], g_single[ids[ok]], rtol=2e-5, atol=1e-7)


def test_halo_train_parity_8_steps_and_zero_compose():
    """Acceptance: 8 free-running halo train steps track the single-device
    run — the FIRST step's loss bit-identical (measured property of the
    CPU build; later steps accumulate ulp-level psum-reduction-order
    drift, the same jitter the ZeRO/DP parity tests carry) — and ZeRO
    stages 1/2 compose bit-consistently with the halo step."""
    mesh = _mesh()
    batch, heads = _giant_batch()
    cfg, model = _halo_model()
    hb, plan = shard_batch_halo(batch, N_DEV, method="sfc", hops=2,
                                head_types=[h.type for h in heads])
    opt = select_optimizer({"type": "AdamW", "learning_rate": 0.01})
    state0 = create_train_state(model, batch, opt, seed=0)

    step1 = jax.jit(make_train_step(model, cfg, opt))
    steph = make_halo_train_step(model, cfg, opt, mesh,
                                 telemetry_metrics=True)
    s1, sh = state0, replicate_state(state0, mesh)
    losses1, lossesh = [], []
    for i in range(8):
        s1, m1 = step1(s1, batch)
        sh, mh = steph(sh, hb)
        losses1.append(float(m1["loss"]))
        lossesh.append(float(mh["loss"]))
    assert lossesh[0] == losses1[0], "first-step loss must be bit-identical"
    np.testing.assert_allclose(lossesh, losses1, rtol=1e-5)
    assert losses1[-1] < losses1[0]  # actually training
    # telemetry counts are the OWNED totals, not halo-duplicated ones
    n_real = int(np.asarray(batch.node_mask).sum())
    e_real = int(np.asarray(batch.edge_mask).sum())
    assert int(mh["nodes_real"]) == n_real
    assert int(mh["edges_real"]) == e_real
    # step-level param parity from IDENTICAL state, under SGD (update
    # linear in the gradient — the same protocol as
    # test_dp_matches_single_device): the psum-assembled halo gradient
    # must reproduce the single-device step leaf-for-leaf.  (Adam would
    # amplify the ~1e-9 round-off noise of analytically-dead leaves — a
    # pre-BatchNorm bias has ZERO gradient — through its eps division;
    # that is an optimizer property, not a partitioning one.)
    sgd = select_optimizer({"type": "SGD", "learning_rate": 0.05})
    state_sgd = create_train_state(model, batch, sgd, seed=0)
    s1b, _ = jax.jit(make_train_step(model, cfg, sgd))(state_sgd, batch)
    shb, _ = make_halo_train_step(model, cfg, sgd, mesh)(
        replicate_state(state_sgd, mesh), hb)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(s1b.params)),
                    jax.tree_util.tree_leaves(jax.device_get(shb.params))):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-5, atol=1e-7)

    # -- ZeRO compose: sharded-state halo steps match the replicated one --
    from hydragnn_tpu.parallel.zero import (
        consolidate_state,
        zero_shard_state,
    )

    for stage in (1, 2):
        s_z, zs = zero_shard_state(state0, mesh, stage=stage)
        step_z = make_halo_train_step(model, cfg, opt, mesh, zero_specs=zs)
        s_ref = replicate_state(state0, mesh)
        s_z, mz = step_z(s_z, hb)
        _, mref = steph(replicate_state(state0, mesh), hb)
        np.testing.assert_allclose(float(mz["loss"]), float(mref["loss"]),
                                   rtol=1e-6)
        # consolidated params return to full unpadded shapes
        back = consolidate_state(s_z, zs, mesh)
        assert [np.shape(x) for x in jax.tree_util.tree_leaves(
                    jax.device_get(back.params))] == \
               [np.shape(x) for x in jax.tree_util.tree_leaves(
                    jax.device_get(state0.params))]


def test_halo_residency_and_no_full_allgather_hlo():
    """The memory claim, asserted two ways: (1) MEASURED per-device bytes
    of the placed node features are n_local rows (= N/D rounded), not N;
    (2) the compiled halo step's HLO contains NO node-feature buffer of
    the full padded [N, F]/[N, hidden] size — while the gspmd baseline's
    compiled forward does (its GSPMD all-gather), which is exactly why it
    is a baseline and not a memory win."""
    from hydragnn_tpu.parallel.zero import measured_device_bytes

    mesh = _mesh()
    # big enough that the bucketed halo buffer is small next to N (at toy
    # sizes the po2 D x halo_pair padding dominates — the waste stat the
    # telemetry block reports)
    batch, heads = _giant_batch(n1=800, n2=40)
    cfg, model = _halo_model()
    hb, plan = shard_batch_halo(batch, N_DEV, method="sfc", hops=2,
                                head_types=[h.type for h in heads])
    n_full = batch.x.shape[0]
    n_real = int(np.asarray(batch.node_mask).sum())
    chunk = -(-n_real // N_DEV)
    assert chunk <= plan.n_local < chunk + 8
    assert plan.ext_n == plan.n_local + N_DEV * plan.halo_pair + 1
    # the acceptance bound: per-device node rows <= N/D + halo_max
    halo_max = N_DEV * plan.halo_pair
    assert plan.ext_n <= chunk + 8 + halo_max + 1
    assert plan.ext_n < n_full  # the whole point

    # (1) measured residency of the placed batch: x rows per device are
    # the N/D chunk, NOT N
    sharded_x = jax.device_put(
        np.asarray(hb.x), NamedSharding(mesh, P("data")))
    per_dev = measured_device_bytes(sharded_x, mesh.devices.flat[0])
    assert per_dev == plan.n_local * batch.x.shape[1] * 4
    assert per_dev <= (n_real / N_DEV + 8) * batch.x.shape[1] * 4

    # (2) HLO buffer assertion: the compiled halo step contains NO buffer
    # with the full padded node count as a dimension, while the gspmd
    # baseline's compiled forward DOES (its GSPMD all-gather of the node
    # array) — which is exactly why gspmd is a baseline, not a memory win
    import re

    opt = select_optimizer({"type": "AdamW", "learning_rate": 0.01})
    state = create_train_state(model, batch, opt, seed=0)
    steph = make_halo_train_step(model, cfg, opt, mesh)
    hlo = steph.lower(replicate_state(state, mesh), hb).compile().as_text()

    def leading_dims(text):
        return {int(m.group(1))
                for m in re.finditer(r"f32\[(\d+),(\d+)\]", text)}

    assert n_full not in leading_dims(hlo), \
        "halo step HLO materializes a full [N, F] node buffer"

    fwd = make_sharded_forward(model, mesh)
    variables = {"params": state.params, "batch_stats": state.batch_stats}
    hlo_g = fwd.lower(variables, shard_batch(batch, mesh)).compile().as_text()
    assert n_full in leading_dims(hlo_g), \
        "gspmd baseline should show full [N, F] buffers (the all-gather) " \
        "— if this ever passes, promote it"


# ---------------------------------------------------------------------------
# knobs / config / trainer e2e
# ---------------------------------------------------------------------------


def test_graph_shard_knobs_env_finalize(monkeypatch):
    for spell, want in ((0, "off"), ("", "off"), ("off", "off"),
                        ("false", "off"), (1, "halo"), ("halo", "halo"),
                        ("true", "halo"), ("gspmd", "gspmd")):
        assert check_graph_shard_backend(spell) == want
    with pytest.raises(ValueError):
        check_graph_shard_backend("sharded")
    monkeypatch.delenv("HYDRAGNN_GRAPH_SHARD", raising=False)
    monkeypatch.delenv("HYDRAGNN_GRAPH_SHARD_METHOD", raising=False)
    monkeypatch.delenv("HYDRAGNN_GRAPH_SHARD_HOPS", raising=False)
    monkeypatch.delenv("HYDRAGNN_GRAPH_SHARD_HALO_MAX", raising=False)
    c = GraphShardConfig.from_training({})
    assert (c.backend, c.method, c.hops, c.halo_max) == ("off", "sfc", 0, 0)
    c = GraphShardConfig.from_training(
        {"graph_shard": "halo", "graph_shard_method": "bfs",
         "graph_shard_hops": 3})
    assert (c.backend, c.method, c.hops) == ("halo", "bfs", 3)
    # env wins, in both directions; set-but-empty falls through
    monkeypatch.setenv("HYDRAGNN_GRAPH_SHARD", "gspmd")
    assert GraphShardConfig.from_training(
        {"graph_shard": "halo"}).backend == "gspmd"
    monkeypatch.setenv("HYDRAGNN_GRAPH_SHARD", "0")
    assert GraphShardConfig.from_training(
        {"graph_shard": "halo"}).backend == "off"
    monkeypatch.setenv("HYDRAGNN_GRAPH_SHARD", "")
    assert GraphShardConfig.from_training(
        {"graph_shard": "halo"}).backend == "halo"
    monkeypatch.setenv("HYDRAGNN_GRAPH_SHARD_HOPS", "4")
    assert GraphShardConfig.from_training({}).hops == 4
    monkeypatch.delenv("HYDRAGNN_GRAPH_SHARD_HOPS")
    with pytest.raises(ValueError):
        GraphShardConfig.from_training({"graph_shard_method": "hilbert"})

    # finalize writes the defaults back + validates (REG005 contract)
    from hydragnn_tpu.config.config import DatasetStats, finalize

    def _cfg_dict(**training):
        return {"NeuralNetwork": {
            "Architecture": {"model_type": "SAGE", "hidden_dim": 8,
                             "num_conv_layers": 2, "output_heads": {}},
            "Variables_of_interest": {"type": ["graph"], "output_index": [0],
                                      "output_dim": [1],
                                      "input_node_features": [0]},
            "Training": {"num_epoch": 1, "batch_size": 4, **training},
        }}

    stats = DatasetStats(num_nodes_sample=10, graph_size_variable=False)
    out = finalize(_cfg_dict(), stats)["NeuralNetwork"]["Training"]
    for k, v in graph_shard_training_defaults().items():
        assert out[k] == v
    out = finalize(_cfg_dict(graph_shard=1), stats)
    assert out["NeuralNetwork"]["Training"]["graph_shard"] == "halo"
    with pytest.raises(ValueError):
        finalize(_cfg_dict(graph_shard="maybe"), stats)

    # an explicit halo cap the partition cannot fit RAISES (never truncates)
    batch, _ = _giant_batch()
    with pytest.raises(ValueError, match="halo"):
        build_shard_plan(batch, N_DEV, method="block", hops=2, halo_max=1)


def test_trainer_halo_e2e_with_telemetry_teleview_and_resume(
        tmp_path, monkeypatch, capsys):
    """The wired path end-to-end: Training.graph_shard=halo through
    train_validate_test on the 8-device mesh — loss drops, the pipeline
    record carries the backend, the telemetry `sharding` event carries the
    partition stats, teleview renders them, and a chaos-preempted halo run
    resumes BIT-identically (the resume bundle is shard-agnostic: state is
    replicated, only DATA is partitioned)."""
    from tests.test_resilience import _Loaders, _fresh_skeleton, _run
    from hydragnn_tpu.resilience import load_resume_bundle, resume_dir

    monkeypatch.delenv("HYDRAGNN_GRAPH_SHARD", raising=False)
    monkeypatch.delenv("HYDRAGNN_CHAOS_PREEMPT_STEP", raising=False)
    monkeypatch.setenv("HYDRAGNN_TELEMETRY", "1")
    monkeypatch.setenv("HYDRAGNN_TELEMETRY_SINKS", "jsonl")
    loaders = _Loaders(n_train=16, batch_size=8)

    state_h, hist_h = _run(loaders, tmp_path, "ghalo", num_epoch=2,
                           use_mesh_dp=True,
                           training_extra={"graph_shard": "halo"})
    assert hist_h["pipeline"]["graph_shard"] == "halo"
    assert hist_h["train"][-1] < hist_h["train"][0]

    events = os.path.join(str(tmp_path), "ghalo", "telemetry",
                          "events.jsonl")
    recs = [json.loads(l) for l in open(events) if l.strip()]
    shard = [r for r in recs if r.get("event") == "sharding"][-1]
    gs = shard["graph_shard"]
    assert gs["backend"] == "halo" and gs["n_shards"] == N_DEV
    assert gs["cut_edge_pct"] >= 0 and gs["halo_pair"] >= 1
    assert "node_imbalance" in gs and "halo_waste_pct" in gs

    import tools.teleview as teleview

    teleview.main([events])
    out = capsys.readouterr().out
    assert "graph_shard=halo" in out
    assert "partition:" in out

    # chaos-preempt mid-run, resume, bit parity vs the uninterrupted run
    monkeypatch.setenv("HYDRAGNN_CHAOS_PREEMPT_STEP", "2")
    _, hist_v = _run(loaders, tmp_path, "gvictim", num_epoch=2,
                     use_mesh_dp=True,
                     training_extra={"graph_shard": "halo"})
    assert hist_v.get("preempted") is True
    monkeypatch.delenv("HYDRAGNN_CHAOS_PREEMPT_STEP")
    bundle = load_resume_bundle(_fresh_skeleton(loaders),
                                resume_dir(str(tmp_path), "gvictim"))
    assert bundle is not None
    state_r, meta = bundle
    assert meta["pipeline"]["graph_shard"] == "halo"
    state_c, hist_c = _run(loaders, tmp_path, "gvictim", num_epoch=2,
                           use_mesh_dp=True,
                           training_extra={"graph_shard": "halo"},
                           resume_meta=meta, state=state_r)
    assert "preempted" not in hist_c
    for a, b in zip(
            jax.tree_util.tree_leaves(jax.device_get(state_h.params)),
            jax.tree_util.tree_leaves(jax.device_get(state_c.params))):
        assert np.array_equal(np.asarray(a), np.asarray(b))


def test_trainer_graph_shard_fallbacks_warn(tmp_path, monkeypatch):
    """Graph sharding requested where it cannot apply falls back LOUDLY:
    the local-jit path warns + emits the health event, and an unsupported
    model (DimeNet-class) on the mesh path does the same."""
    from tests.test_resilience import _Loaders, _run

    monkeypatch.delenv("HYDRAGNN_GRAPH_SHARD", raising=False)
    loaders = _Loaders(n_train=16, batch_size=8)
    with pytest.warns(UserWarning, match="local-jit path"):
        _, hist = _run(loaders, tmp_path, "glocal", num_epoch=1,
                       use_mesh_dp=False,
                       training_extra={"graph_shard": "halo"})
    assert hist["pipeline"]["graph_shard"] == "off"

    # a halo shallower than the conv stack would train on silently wrong
    # boundary neighborhoods — must raise, not warn
    with pytest.raises(ValueError, match="shallower"):
        _run(loaders, tmp_path, "ghops", num_epoch=1, use_mesh_dp=True,
             training_extra={"graph_shard": "halo", "graph_shard_hops": 1})
