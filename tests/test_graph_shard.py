"""Node-sharded (GSPMD) execution: one graph batch partitioned across the
8-device CPU mesh must produce the same forward outputs and loss gradients
as single-device execution — XLA inserts the cross-shard collectives, the
model code is unchanged."""

import numpy as np
import pytest

import jax
import jax.numpy as jnp
from jax.sharding import Mesh

from hydragnn_tpu.graph.batch import GraphSample, HeadSpec, PadSpec, collate
from hydragnn_tpu.graph.neighborlist import radius_graph
from hydragnn_tpu.models.base import GraphHeadCfg, ModelConfig, NodeHeadCfg
from hydragnn_tpu.models.create import create_model
from hydragnn_tpu.parallel.graph_shard import (
    make_sharded_forward,
    shard_batch,
)


def _mesh():
    devs = jax.devices()
    if len(devs) < 8:
        pytest.skip("needs the 8-device CPU mesh (conftest sets XLA_FLAGS)")
    return Mesh(np.array(devs[:8]), ("data",))


def _batch_and_model(model_type="SAGE", n_graphs=8, npg=16):
    rng = np.random.RandomState(0)
    samples = []
    for _ in range(n_graphs):
        pos = rng.rand(npg, 3).astype(np.float32) * 3.0
        x = rng.rand(npg, 1).astype(np.float32)
        ei = radius_graph(pos, radius=1.5, max_neighbours=8)
        samples.append(GraphSample(
            x=x, pos=pos, edge_index=ei,
            graph_y=rng.rand(1).astype(np.float32), node_y=x))
    # node/edge dims divisible by 8 so they shard; graph dim deliberately
    # NOT divisible so the replicate-when-indivisible fallback is exercised
    max_e = max(s.num_edges for s in samples)
    pad = PadSpec(num_nodes=n_graphs * npg + 8,
                  num_edges=-(-(n_graphs * max_e + 1) // 8) * 8,
                  num_graphs=n_graphs + 9)
    heads = [HeadSpec("energy", "graph", 1), HeadSpec("charge", "node", 1)]
    batch = collate(samples, pad, heads)

    cfg = ModelConfig(
        model_type=model_type, input_dim=1, hidden_dim=16,
        output_dim=(1, 1), output_type=("graph", "node"),
        graph_head=GraphHeadCfg(1, 16, 1, (16,)),
        node_head=NodeHeadCfg(num_headlayers=1, dim_headlayers=(16,),
                              type="mlp"),
        task_weights=(1.0, 1.0), num_conv_layers=2,
        pna_avg_deg_log=1.2, pna_avg_deg_lin=3.0,
        num_gaussians=8, num_filters=16, radius=1.5, max_neighbours=8)
    model = create_model(cfg)
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        batch, train=False)
    return model, variables, batch


def test_sharded_batch_is_actually_sharded():
    mesh = _mesh()
    _, _, batch = _batch_and_model()
    sb = shard_batch(batch, mesh)
    shards = sb.x.addressable_shards
    assert len(shards) == 8
    assert shards[0].data.shape[0] == batch.x.shape[0] // 8
    # the graph dim (17) doesn't divide 8 -> graph arrays stay REPLICATED
    assert batch.graph_mask.shape[0] % 8 != 0
    gshards = sb.graph_mask.addressable_shards
    assert all(s.data.shape == batch.graph_mask.shape for s in gshards)


@pytest.mark.parametrize("model_type", ["SAGE", "GIN", "PNA", "SchNet"])
def test_sharded_forward_matches_single_device(model_type):
    mesh = _mesh()
    model, variables, batch = _batch_and_model(model_type)
    want = model.apply(variables, batch, train=False)

    fwd = make_sharded_forward(model, mesh)
    got = fwd(variables, shard_batch(batch, mesh))
    for w, g in zip(want, got):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-5, atol=2e-5)


def test_sharded_grad_matches_single_device():
    mesh = _mesh()
    model, variables, batch = _batch_and_model("SAGE")

    def loss(variables, b):
        out = model.apply(variables, b, train=False)
        return (jnp.sum((out[0] * b.graph_mask[:, None]) ** 2)
                + jnp.sum((out[1] * b.node_mask[:, None]) ** 2))

    g_want = jax.grad(loss)(variables, batch)
    from jax.sharding import NamedSharding, PartitionSpec as P

    repl = NamedSharding(mesh, P())
    g_got = jax.jit(jax.grad(loss), in_shardings=(repl, None),
                    out_shardings=repl)(variables, shard_batch(batch, mesh))
    flat_w, _ = jax.tree_util.tree_flatten(g_want)
    flat_g, _ = jax.tree_util.tree_flatten(g_got)
    for w, g in zip(flat_w, flat_g):
        np.testing.assert_allclose(np.asarray(g), np.asarray(w),
                                   rtol=2e-4, atol=2e-4)
