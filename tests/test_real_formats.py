"""Real-dataset format parsers against hand-written fixtures in the exact
published layouts (the archives themselves cannot be downloaded here):

- OC20 extxyz frames (ASE extended-XYZ with Lattice/Properties/energy/tags
  — what the reference reads via AtomsToGraphs,
  examples/open_catalyst_2020/utils/atoms_to_graphs.py)
- MD17 npz (sgdml keys E/F/R/z — reference examples/md17/md17.py:15-23)
- MPTrj JSON (pymatgen structure dicts — reference
  examples/mptrj/train.py:76-151)
- ANI-1x HDF5 (formula buckets with NaN holes — reference
  examples/ani1_x/train.py:126-146)

Each format is checked twice: the parser itself, and the example driver's
conversion of parsed frames into GraphSamples.
"""

import importlib.util
import json
import os

import numpy as np
import pytest

from hydragnn_tpu.data import formats


def _load_example(name):
    path = os.path.join(
        os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
        "examples", name, "train.py")
    spec = importlib.util.spec_from_file_location(f"{name}_fmt_train", path)
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


# ---------------------------------------------------------------------------
# extxyz
# ---------------------------------------------------------------------------

_EXTXYZ = '''3
Lattice="10.0 0.0 0.0 0.0 10.0 0.0 0.0 0.0 10.0" Properties=species:S:1:pos:R:3:forces:R:3:tags:I:1 energy=-12.345 free_energy=-12.350 pbc="T T T"
Cu 0.00000 0.00000 0.00000 0.01000 -0.02000 0.00300 0
Cu 1.80500 1.80500 0.00000 -0.01000 0.02000 -0.00300 0
O 0.90000 0.90000 1.50000 0.00000 0.00000 -0.10000 1
2
Lattice="8.0 0.0 0.0 0.0 8.0 0.0 0.0 0.0 8.0" Properties=species:S:1:pos:R:3 energy=-3.5
H 0.0 0.0 0.0
H 0.0 0.0 0.74
'''


def test_extxyz_frames(tmp_path):
    p = tmp_path / "frames.extxyz"
    p.write_text(_EXTXYZ)
    frames = formats.load_extxyz(str(p))
    assert len(frames) == 2
    f0, f1 = frames
    assert f0.num_nodes == 3
    assert np.allclose(f0.z, [29, 29, 8])
    assert f0.cell.shape == (3, 3) and f0.cell[0, 0] == 10.0
    assert f0.energy == pytest.approx(-12.345)
    assert f0.forces.shape == (3, 3)
    assert f0.forces[2, 2] == pytest.approx(-0.1)
    assert np.allclose(f0.tags, [0, 0, 1])
    assert f1.num_nodes == 2 and f1.forces is None and f1.tags is None
    assert f1.energy == pytest.approx(-3.5)
    assert f1.pos[1, 2] == pytest.approx(0.74)


def test_extxyz_directory_and_oc20_wire(tmp_path):
    (tmp_path / "a.extxyz").write_text(_EXTXYZ)
    frames = formats.load_extxyz(str(tmp_path))
    assert len(frames) == 2
    oc = _load_example("open_catalyst_2020")
    samples = oc.load_frames(str(tmp_path), radius=4.0, max_neighbours=12)
    assert len(samples) == 2
    s0 = samples[0]
    assert s0.x.shape == (3, 2)            # [Z, tag]
    assert s0.x[2, 1] == 1.0               # adsorbate tag survives
    assert s0.edge_index.shape[0] == 2 and s0.edge_index.shape[1] > 0
    # energies were standardized over the 2-frame corpus
    e = np.asarray([s.graph_y[0] for s in samples])
    assert abs(e.mean()) < 1e-6


# ---------------------------------------------------------------------------
# MD17 npz
# ---------------------------------------------------------------------------


def _write_md17(tmp_path, n_frames=5, n_atoms=4):
    rng = np.random.RandomState(0)
    z = np.asarray([6, 1, 1, 8][:n_atoms])
    R = rng.rand(n_frames, n_atoms, 3) * 2.0
    E = rng.rand(n_frames, 1) * -100.0    # distribution ships [F, 1]
    F = rng.randn(n_frames, n_atoms, 3)
    p = tmp_path / "md17_uracil.npz"
    np.savez(p, z=z, R=R, E=E, F=F, name="uracil", theory="DFT")
    return p, z, R, E, F


def test_md17_npz(tmp_path):
    p, z, R, E, F = _write_md17(tmp_path)
    frames = formats.load_md17_npz(str(p))
    assert len(frames) == 5
    assert np.allclose(frames[0].z, z)
    assert np.allclose(frames[3].pos, R[3])
    assert frames[2].energy == pytest.approx(float(E[2, 0]))
    assert np.allclose(frames[4].forces, F[4])


def test_md17_example_wire(tmp_path):
    p, z, R, E, F = _write_md17(tmp_path)
    md17 = _load_example("md17")
    samples = md17.load_md17_npz(str(p), max_frames=3, radius=2.5)
    assert len(samples) == 3
    assert samples[0].x.shape == (len(z), 1)
    assert samples[0].node_y.shape == (len(z), 3)
    assert "grad_energy_post_scaling_factor" in samples[0].extras


# ---------------------------------------------------------------------------
# MPTrj JSON
# ---------------------------------------------------------------------------


def _mptrj_blob():
    lattice = [[4.0, 0.0, 0.0], [0.0, 4.0, 0.0], [0.0, 0.0, 4.0]]
    def site(el, abc):
        return {"species": [{"element": el, "occu": 1}], "abc": abc,
                "label": el}
    frame = {
        "structure": {
            "@module": "pymatgen.core.structure",
            "@class": "Structure",
            "lattice": {"matrix": lattice, "a": 4.0, "b": 4.0, "c": 4.0},
            "sites": [site("Fe", [0.0, 0.0, 0.0]),
                      site("O", [0.5, 0.5, 0.0]),
                      site("O", [0.5, 0.0, 0.5])],
        },
        "uncorrected_total_energy": -21.0,
        "corrected_total_energy": -21.5,
        "energy_per_atom": -7.0,
        "force": [[0.1, 0.0, 0.0], [-0.05, 0.0, 0.0], [-0.05, 0.0, 0.0]],
        "stress": [[0.0] * 3] * 3,
        "magmom": 2.1,
    }
    return {"mp-999": {"mp-999-0": frame}}


def test_mptrj_json(tmp_path):
    p = tmp_path / "MPtrj_2022.9_full.json"
    p.write_text(json.dumps(_mptrj_blob()))
    frames = formats.load_mptrj_json(str(p))
    assert len(frames) == 1
    fr = frames[0]
    assert np.allclose(fr.z, [26, 8, 8])
    assert fr.energy == pytest.approx(-7.0)          # energy_per_atom default
    assert np.allclose(fr.pos[1], [2.0, 2.0, 0.0])   # abc @ lattice
    assert fr.forces.shape == (3, 3)
    total = formats.load_mptrj_json(str(p), energy_per_atom=False)
    assert total[0].energy == pytest.approx(-21.5)   # corrected total


def test_mptrj_streaming_iterator(tmp_path):
    # multi-entry object streamed with a tiny chunk size so every refill
    # path (mid-key, mid-value, value-at-buffer-edge) is exercised
    blob = {}
    for i in range(7):
        blob[f"mp-{i}"] = {"a": [i] * 10, "b": {"c": "x" * 30}, "n": i * 1.5}
    p = tmp_path / "obj.json"
    p.write_text(json.dumps(blob))
    for chunk in (1, 3, 17, 1 << 20):
        items = dict(formats._iter_json_object_items(str(p), chunk=chunk))
        assert items == blob, f"chunk={chunk}"
    bad = tmp_path / "bad.json"
    bad.write_text('{"k": {"unterminated": 1')
    with pytest.raises(ValueError):
        list(formats._iter_json_object_items(str(bad), chunk=8))
    notobj = tmp_path / "arr.json"
    notobj.write_text("[1, 2]")
    with pytest.raises(ValueError):
        list(formats._iter_json_object_items(str(notobj)))


def test_mptrj_example_wire(tmp_path):
    p = tmp_path / "MPtrj_2022.9_full.json"
    p.write_text(json.dumps(_mptrj_blob()))
    mptrj = _load_example("mptrj")
    samples = mptrj.load_mptrj(str(p), radius=3.0, max_neighbours=12)
    assert len(samples) == 1
    s = samples[0]
    assert s.x.shape == (3, 3)                      # [z, d1, d2]
    assert s.node_y.shape == (3, 6)                 # [z, d1, d2, fx, fy, fz]
    assert s.cell is not None


# ---------------------------------------------------------------------------
# ANI-1x HDF5
# ---------------------------------------------------------------------------


def _write_ani1x(tmp_path):
    h5py = pytest.importorskip("h5py")
    p = tmp_path / "ani1x-release.h5"
    rng = np.random.RandomState(1)
    with h5py.File(p, "w") as f:
        g = f.create_group("C1H4")
        g["atomic_numbers"] = np.asarray([6, 1, 1, 1, 1])
        coords = rng.rand(4, 5, 3)
        g["coordinates"] = coords
        E = np.asarray([-40.1, np.nan, -40.3, -40.4])
        g["wb97x_dz.energy"] = E
        F = rng.randn(4, 5, 3)
        F[3, 0, 0] = np.nan                        # NaN force -> frame drops
        g["wb97x_dz.forces"] = F
        g2 = f.create_group("O1H2")                # bucket without the key
        g2["atomic_numbers"] = np.asarray([8, 1, 1])
        g2["coordinates"] = rng.rand(2, 3, 3)
    return p, coords, E, F


def test_ani1x_h5(tmp_path):
    p, coords, E, F = _write_ani1x(tmp_path)
    frames = formats.load_ani1x_h5(str(p))
    # frames 1 (NaN energy) and 3 (NaN force) dropped; O1H2 lacks the key
    assert len(frames) == 2
    assert frames[0].energy == pytest.approx(-40.1)
    assert frames[1].energy == pytest.approx(-40.3)
    assert np.allclose(frames[1].pos, coords[2])
    assert np.allclose(frames[1].forces, F[2])
    # energy-only ingest keeps NaN-force frames
    eonly = formats.load_ani1x_h5(str(p), forces_key=None)
    assert len(eonly) == 3


def test_ani1x_example_wire(tmp_path):
    p, coords, E, F = _write_ani1x(tmp_path)
    md17 = _load_example("md17")
    samples = md17.load_md17_npz(str(p), max_frames=2, radius=3.0)
    assert len(samples) == 2
    assert samples[0].x.shape == (5, 1)
    assert samples[0].node_y.shape == (5, 3)
