"""Native runtime tests: gpack container round-trip (native + numpy readers)
and the DistDataset store incl. a real TCP remote get against the local
server (the single-host analog of DDStore remote reads)."""

import ctypes
import pickle

import numpy as np
import pytest

from hydragnn_tpu.graph.batch import GraphSample
from hydragnn_tpu.native import available, load_library


def _samples(n=10, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        nn = rng.randint(3, 9)
        ne = rng.randint(2, 12)
        out.append(GraphSample(
            x=rng.rand(nn, 3).astype(np.float32),
            pos=rng.rand(nn, 3).astype(np.float32),
            edge_index=rng.randint(0, nn, (2, ne)).astype(np.int32),
            graph_y=rng.rand(2).astype(np.float32),
            node_y=rng.rand(nn, 3).astype(np.float32),
        ))
    return out


def test_native_library_builds():
    assert available(), "native hydrastore library failed to build"


@pytest.mark.parametrize("use_native", [True, False])
def test_gpack_roundtrip(tmp_path, use_native):
    from hydragnn_tpu.data.gpack import GpackDataset, GpackWriter

    samples = _samples(12)
    path = str(tmp_path / "ds.gpack")
    GpackWriter(path, rank=0, attrs={
        "pna_deg": [0, 3, 5], "minmax": [[0.0], [1.0]]}).save(samples)

    ds = GpackDataset(path, use_native=use_native)
    assert len(ds) == 12
    assert ds.attrs["pna_deg"] == [0, 3, 5]
    for i in (0, 5, 11):
        got = ds.get(i)
        np.testing.assert_array_equal(got.x, samples[i].x)
        np.testing.assert_array_equal(got.pos, samples[i].pos)
        np.testing.assert_array_equal(got.edge_index, samples[i].edge_index)
        np.testing.assert_array_equal(got.graph_y, samples[i].graph_y)
    ds.close()


def test_gpack_multipart_and_subset(tmp_path):
    from hydragnn_tpu.data.gpack import GpackDataset, GpackWriter

    s0, s1 = _samples(5, seed=1), _samples(7, seed=2)
    base = str(tmp_path / "multi.gpack")
    GpackWriter(base, rank=0).save(s0)
    GpackWriter(base, rank=1).save(s1)

    ds = GpackDataset(base)
    assert len(ds) == 12
    np.testing.assert_array_equal(ds.get(3).x, s0[3].x)
    np.testing.assert_array_equal(ds.get(5).x, s1[0].x)
    np.testing.assert_array_equal(ds.get(11).x, s1[6].x)

    ds.setsubset(5, 12, preload=True)
    assert len(ds) == 7
    np.testing.assert_array_equal(ds.get(0).x, s1[0].x)
    ds.close()


def test_distdataset_local_get():
    from hydragnn_tpu.data.distdataset import DistDataset

    samples = _samples(8, seed=3)
    ds = DistDataset(samples)
    assert len(ds) == 8
    for i in (0, 4, 7):
        got = ds.get(i)
        np.testing.assert_array_equal(got.x, samples[i].x)
    ds.close()


def test_dstore_tcp_remote_get():
    """Exercise the TCP path explicitly against the local server."""
    lib = load_library()
    store = lib.dstore_create(0)
    assert store
    port = lib.dstore_port(store)

    blobs = [pickle.dumps({"i": i, "a": np.arange(i + 1)}) for i in range(5)]
    sizes = np.asarray([len(b) for b in blobs], np.int64)
    lib.dstore_add(store, b"k", b"".join(blobs),
                   sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                   5, 100)  # global indices 100..104

    fd = lib.dstore_connect(b"127.0.0.1", port)
    assert fd >= 0
    buf = ctypes.create_string_buffer(1 << 16)
    for gidx in (100, 103, 104):
        n = lib.dstore_fetch(fd, b"k", gidx, buf, len(buf))
        assert n > 0
        obj = pickle.loads(buf.raw[:n])
        assert obj["i"] == gidx - 100
        np.testing.assert_array_equal(obj["a"], np.arange(gidx - 100 + 1))
    # missing index -> -1
    assert lib.dstore_fetch(fd, b"k", 99, buf, len(buf)) == -1
    lib.dstore_disconnect(fd)
    lib.dstore_destroy(store)


def test_dstore_connect_timeout_unreachable():
    """Connecting to a non-listening port fails fast, not forever."""
    import time

    lib = load_library()
    # grab a port nobody listens on
    import socket as pysock

    s = pysock.socket()
    s.bind(("127.0.0.1", 0))
    dead_port = s.getsockname()[1]
    s.close()

    t0 = time.perf_counter()
    fd = lib.dstore_connect_timeout(b"127.0.0.1", dead_port, 1000)
    dt = time.perf_counter() - t0
    assert fd < 0
    assert dt < 5.0  # refused or timed out well within bounds


def test_dstore_kill_a_peer(tmp_path):
    """A server killed mid-conversation surfaces as a bounded error on the
    client, not a hang or short-read garbage (round-3 VERDICT item 9)."""
    import signal
    import subprocess
    import sys
    import time

    server_src = tmp_path / "server.py"
    server_src.write_text(
        "import ctypes, pickle, sys, time\n"
        "import numpy as np\n"
        "from hydragnn_tpu.native import load_library\n"
        "lib = load_library()\n"
        "store = lib.dstore_create(0)\n"
        "blob = pickle.dumps(np.arange(32))\n"
        "sizes = np.asarray([len(blob)], np.int64)\n"
        "lib.dstore_add(store, b'k', blob,\n"
        "    sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)), 1, 0)\n"
        "print(lib.dstore_port(store), flush=True)\n"
        "time.sleep(600)\n")
    import os

    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    repo = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
    env["PYTHONPATH"] = repo + os.pathsep + env.get("PYTHONPATH", "")
    proc = subprocess.Popen(
        [sys.executable, str(server_src)], stdout=subprocess.PIPE, text=True,
        env=env, cwd=repo)
    try:
        port = int(proc.stdout.readline())
        lib = load_library()
        fd = lib.dstore_connect_timeout(b"127.0.0.1", port, 2000)
        assert fd >= 0
        buf = ctypes.create_string_buffer(1 << 12)
        n = lib.dstore_fetch(fd, b"k", 0, buf, len(buf))
        assert n > 0  # healthy fetch first

        proc.send_signal(signal.SIGKILL)
        proc.wait(timeout=10)

        t0 = time.perf_counter()
        n = lib.dstore_fetch(fd, b"k", 0, buf, len(buf))
        dt = time.perf_counter() - t0
        assert n == -3, f"expected I/O failure code, got {n}"
        assert dt < 10.0
        lib.dstore_disconnect(fd)
    finally:
        if proc.poll() is None:
            proc.kill()


def test_distdataset_dead_owner_raises(monkeypatch):
    """The Python wrapper turns a dead owner into a RuntimeError naming the
    peer, after one reconnect attempt — no silent hang, no assert."""
    import socket as pysock

    from hydragnn_tpu.data.distdataset import DistDataset

    monkeypatch.setenv("HYDRASTORE_TIMEOUT_MS", "800")
    ds = DistDataset(_samples(4), label="deadpeer")
    try:
        # forge a second, dead owner holding global indices 4..7
        s = pysock.socket()
        s.bind(("127.0.0.1", 0))
        dead_port = s.getsockname()[1]
        s.close()
        ds.counts = [4, 4]
        ds.total = 8
        ds.addresses = list(ds.addresses) + [("127.0.0.1", dead_port)]

        with pytest.raises(RuntimeError, match="dstore owner 1"):
            ds.get(6)
    finally:
        ds.close()
