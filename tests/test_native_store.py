"""Native runtime tests: gpack container round-trip (native + numpy readers)
and the DistDataset store incl. a real TCP remote get against the local
server (the single-host analog of DDStore remote reads)."""

import ctypes
import pickle

import numpy as np
import pytest

from hydragnn_tpu.graph.batch import GraphSample
from hydragnn_tpu.native import available, load_library


def _samples(n=10, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for i in range(n):
        nn = rng.randint(3, 9)
        ne = rng.randint(2, 12)
        out.append(GraphSample(
            x=rng.rand(nn, 3).astype(np.float32),
            pos=rng.rand(nn, 3).astype(np.float32),
            edge_index=rng.randint(0, nn, (2, ne)).astype(np.int32),
            graph_y=rng.rand(2).astype(np.float32),
            node_y=rng.rand(nn, 3).astype(np.float32),
        ))
    return out


def test_native_library_builds():
    assert available(), "native hydrastore library failed to build"


@pytest.mark.parametrize("use_native", [True, False])
def test_gpack_roundtrip(tmp_path, use_native):
    from hydragnn_tpu.data.gpack import GpackDataset, GpackWriter

    samples = _samples(12)
    path = str(tmp_path / "ds.gpack")
    GpackWriter(path, rank=0, attrs={
        "pna_deg": [0, 3, 5], "minmax": [[0.0], [1.0]]}).save(samples)

    ds = GpackDataset(path, use_native=use_native)
    assert len(ds) == 12
    assert ds.attrs["pna_deg"] == [0, 3, 5]
    for i in (0, 5, 11):
        got = ds.get(i)
        np.testing.assert_array_equal(got.x, samples[i].x)
        np.testing.assert_array_equal(got.pos, samples[i].pos)
        np.testing.assert_array_equal(got.edge_index, samples[i].edge_index)
        np.testing.assert_array_equal(got.graph_y, samples[i].graph_y)
    ds.close()


def test_gpack_multipart_and_subset(tmp_path):
    from hydragnn_tpu.data.gpack import GpackDataset, GpackWriter

    s0, s1 = _samples(5, seed=1), _samples(7, seed=2)
    base = str(tmp_path / "multi.gpack")
    GpackWriter(base, rank=0).save(s0)
    GpackWriter(base, rank=1).save(s1)

    ds = GpackDataset(base)
    assert len(ds) == 12
    np.testing.assert_array_equal(ds.get(3).x, s0[3].x)
    np.testing.assert_array_equal(ds.get(5).x, s1[0].x)
    np.testing.assert_array_equal(ds.get(11).x, s1[6].x)

    ds.setsubset(5, 12, preload=True)
    assert len(ds) == 7
    np.testing.assert_array_equal(ds.get(0).x, s1[0].x)
    ds.close()


def test_distdataset_local_get():
    from hydragnn_tpu.data.distdataset import DistDataset

    samples = _samples(8, seed=3)
    ds = DistDataset(samples)
    assert len(ds) == 8
    for i in (0, 4, 7):
        got = ds.get(i)
        np.testing.assert_array_equal(got.x, samples[i].x)
    ds.close()


def test_dstore_tcp_remote_get():
    """Exercise the TCP path explicitly against the local server."""
    lib = load_library()
    store = lib.dstore_create(0)
    assert store
    port = lib.dstore_port(store)

    blobs = [pickle.dumps({"i": i, "a": np.arange(i + 1)}) for i in range(5)]
    sizes = np.asarray([len(b) for b in blobs], np.int64)
    lib.dstore_add(store, b"k", b"".join(blobs),
                   sizes.ctypes.data_as(ctypes.POINTER(ctypes.c_int64)),
                   5, 100)  # global indices 100..104

    fd = lib.dstore_connect(b"127.0.0.1", port)
    assert fd >= 0
    buf = ctypes.create_string_buffer(1 << 16)
    for gidx in (100, 103, 104):
        n = lib.dstore_fetch(fd, b"k", gidx, buf, len(buf))
        assert n > 0
        obj = pickle.loads(buf.raw[:n])
        assert obj["i"] == gidx - 100
        np.testing.assert_array_equal(obj["a"], np.arange(gidx - 100 + 1))
    # missing index -> -1
    assert lib.dstore_fetch(fd, b"k", 99, buf, len(buf)) == -1
    lib.dstore_disconnect(fd)
    lib.dstore_destroy(store)
