"""Elastic training (resilience/elastic.py + trainer wiring) — tier-1.

The load-bearing claims, each asserted here:

- consolidate -> reshard -> consolidate is BIT-EXACT across mesh sizes
  and ZeRO stages — the "resize loses no bit" core (parallel/zero.py);
- the StreamPlan re-partitions the SAME seeded global order across a
  resize: exactly-once coverage at any world size, identical per-step
  global sample sets when the global batch is preserved, fingerprint
  invariant under ``elastic_handoff``;
- ``resolve_resume``'s decision matrix: dormant same-shape pass-through,
  strict refusal naming both shapes and the knob, epoch-boundary admit,
  exact mid-epoch unit conversion, loud round-up, legacy-meta synthesis;
- the trainer end to end: a chaos-armed resize stops at the epoch
  boundary with a world-stamped bundle; a different-shape relaunch is
  refused under ``strict`` and admitted under ``epoch``, and the
  admitted run's loss trajectory matches an uninterrupted fixed-shape
  run; a same-shape resume stays bit-identical even under the
  permissive policy (the elastic path is provably dormant);
- ZeRO composes: a bundle saved at N=4/stage-1 resumes at M=8/stage-2
  mid-epoch with an exact unit conversion;
- streaming store opens retry with bounded backoff
  (``stream_open_retry`` events) BEFORE the in-memory fallback.
"""

import os

import jax
import numpy as np
import pytest

from hydragnn_tpu.data.stream.plan import StreamPlan
from hydragnn_tpu.parallel.mesh import make_mesh
from hydragnn_tpu.parallel.zero import consolidate_state, reshard_state
from hydragnn_tpu.resilience import (
    ElasticCoordinator,
    ElasticWorldMismatchError,
    check_elastic_policy,
    elastic_policy_from_training,
    load_resume_bundle,
    resolve_resume,
    resume_dir,
    world_block,
)
from hydragnn_tpu.resilience.chaos import Chaos, _parse_elastic_spec
from hydragnn_tpu.resilience.elastic import saved_world_from_meta
from hydragnn_tpu.train.trainer import train_validate_test

from tests.test_resilience import (
    _Loaders,
    _fresh_skeleton,
    _leaves_equal,
    _model,
    _run,
)

N_DEV = 8


class _Health:
    """Telemetry stub capturing health events (kind, fields)."""

    def __init__(self):
        self.events = []

    def health(self, kind, **fields):
        self.events.append((kind, fields))

    def kinds(self):
        return [k for k, _ in self.events]


# ---------------------------------------------------------------------------
# reshard: the state-side resize primitive
# ---------------------------------------------------------------------------


@pytest.mark.parametrize("stage", [0, 1, 2])
def test_reshard_roundtrip_bit_exact_across_mesh_sizes(stage):
    """consolidate(reshard(x, mesh_M)) == x for M < N and M > N (including
    non-divisible extents 3 and 5) at every ZeRO stage — the resize
    preserves every bit of the train state by construction."""
    assert len(jax.devices()) == N_DEV
    loaders = _Loaders(n_train=16, batch_size=8)
    base = jax.device_get(_fresh_skeleton(loaders))
    devs = jax.devices()

    def _consolidated(st, zs, mesh):
        return jax.device_get(
            consolidate_state(st, zs, mesh) if zs is not None else st)

    prev = base
    for extent in (4, 3, 5, 8):
        mesh = make_mesh(devs[:extent])
        st, zs = reshard_state(prev, mesh, stage=stage)
        if stage == 0:
            assert zs is None
        back = _consolidated(st, zs, mesh)
        assert _leaves_equal(back, base)
        prev = back  # chain resizes: 8 -> 4 -> 3 -> 5 -> 8


# ---------------------------------------------------------------------------
# stream plan: the data-side resize primitive
# ---------------------------------------------------------------------------


def test_stream_plan_elastic_repartition_exactly_once():
    """elastic_handoff(M, rank') re-partitions the SAME seeded global
    permutation: every index exactly once per epoch at any world size,
    and the fingerprint (global-order identity) is shape-invariant."""
    n, seed = 101, 9
    base = StreamPlan(n, seed=seed, rank=0, world_size=4)
    for ws_new in (3, 5, 1):
        handed = [base.elastic_handoff(ws_new, r) for r in range(ws_new)]
        assert all(p.fingerprint() == base.fingerprint() for p in handed)
        for epoch in (0, 3):
            shares = [p.epoch_order(epoch) for p in handed]
            joined = np.concatenate(shares)
            assert len(joined) == -(-n // ws_new) * ws_new  # wrap-padded
            assert set(joined.tolist()) == set(range(n))
    # a different seed IS a different global order
    assert StreamPlan(n, seed=seed + 1).fingerprint() != base.fingerprint()


def test_stream_plan_constant_global_batch_same_step_sets():
    """With the global batch G preserved across a resize, step s draws the
    SAME global sample set at world 4 (B=6) and world 3 (B=8) — the
    invariant that makes post-resize loss trajectories comparable."""
    n, G = 96, 24
    a = [StreamPlan(n, seed=5, rank=r, world_size=4) for r in range(4)]
    b = [a[0].elastic_handoff(3, r) for r in range(3)]
    for epoch in (0, 2):
        for s in range(n // G):
            set_a = {int(i) for p in a
                     for i in p.epoch_order(epoch)[s * 6:(s + 1) * 6]}
            set_b = {int(i) for p in b
                     for i in p.epoch_order(epoch)[s * 8:(s + 1) * 8]}
            assert set_a == set_b


def test_stream_loader_exposes_plan_fingerprint(tmp_path):
    from hydragnn_tpu.data.gpack import GpackDataset, GpackWriter
    from hydragnn_tpu.data.stream.loader import StreamingGraphLoader
    from hydragnn_tpu.graph.batch import HeadSpec

    from tests.test_stream import _samples

    store = GpackDataset(
        GpackWriter(str(tmp_path / "s.gpack")).save(_samples(10)))
    try:
        loader = StreamingGraphLoader(
            store, np.arange(10), [HeadSpec("e", "graph", 1)], 5, window=6,
            shuffle=True, seed=13)
        fp = loader.plan().fingerprint()
        assert isinstance(fp, str) and len(fp) == 16
        assert loader.plan().describe()["fingerprint"] == fp
    finally:
        store.close()


# ---------------------------------------------------------------------------
# resolve_resume decision matrix
# ---------------------------------------------------------------------------


def _world(ws=1, dp=8, zero=0, units=None, fp=None):
    return world_block(world_size=ws, n_local_devices=dp, dp_extent=dp,
                       zero_stage=zero, epoch_units=units,
                       plan_fingerprint=fp)


def test_resolve_resume_decision_matrix():
    launched = _world(dp=8, units=2)
    # same shape: dormant pass-through of the saved position, exactly
    meta = {"epoch": 3, "items_consumed": 1, "world": _world(dp=8, units=2)}
    d = resolve_resume(meta, policy="strict", launched=launched)
    assert (d.elastic, d.start_epoch, d.skip_first) == (False, 3, 1)
    assert d.reason == "same_shape"

    # strict refusal names both shapes and the knob, emits elastic_refuse
    tel = _Health()
    mism = {"epoch": 3, "items_consumed": 0, "world": _world(dp=4, units=2)}
    with pytest.raises(ElasticWorldMismatchError) as ei:
        resolve_resume(mism, policy="strict", launched=launched,
                       telemetry=tel)
    assert "dp_extent=4" in str(ei.value) and "dp_extent=8" in str(ei.value)
    assert "elastic_resume" in str(ei.value)
    assert tel.kinds() == ["elastic_refuse"]

    # epoch policy: boundary bundles resume directly
    d = resolve_resume(mism, policy="epoch", launched=launched)
    assert (d.elastic, d.start_epoch, d.skip_first,
            d.rounded) == (True, 3, 0, False)
    assert d.reason == "epoch_boundary"

    # mid-epoch exact conversion: 1 of 2 saved units == 2 of 4 new units
    mid = {"epoch": 3, "items_consumed": 1, "world": _world(dp=4, units=2)}
    d = resolve_resume(mid, policy="epoch",
                       launched=_world(dp=8, units=4))
    assert (d.start_epoch, d.skip_first, d.rounded) == (3, 2, False)
    assert d.reason == "mid_epoch_exact"

    # inexact position rounds UP to the next boundary, loudly flagged
    mid3 = {"epoch": 3, "items_consumed": 1, "world": _world(dp=4, units=3)}
    d = resolve_resume(mid3, policy="epoch",
                       launched=_world(dp=8, units=4))
    assert (d.start_epoch, d.skip_first, d.rounded) == (4, 0, True)
    assert d.reason == "mid_epoch_rounded"

    # a fully-consumed epoch is positionally a boundary
    done = {"epoch": 3, "items_consumed": 2, "world": _world(dp=4, units=2)}
    d = resolve_resume(done, policy="epoch", launched=launched)
    assert (d.start_epoch, d.skip_first) == (4, 0)
    assert d.reason == "completed_epoch"

    # unknown units (legacy bundle): mid-epoch cannot convert -> round up
    legacy = {"epoch": 2, "items_consumed": 1, "world_size": 2,
              "pipeline": {"n_local_devices": 4, "use_mesh_dp": True,
                           "zero_stage": 1}}
    assert saved_world_from_meta(legacy)["dp_extent"] == 8
    d = resolve_resume(legacy, policy="epoch", launched=launched)
    assert (d.start_epoch, d.skip_first, d.rounded) == (3, 0, True)

    # mismatched stream fingerprints cannot be mapped — refuse even
    # under the permissive policy (and even at the same shape)
    fp_a = {"epoch": 1, "items_consumed": 0,
            "world": _world(dp=4, units=2, fp="aaaa")}
    with pytest.raises(ElasticWorldMismatchError, match="fingerprint"):
        resolve_resume(fp_a, policy="epoch",
                       launched=_world(dp=8, units=2, fp="bbbb"))
    same_fp = {"epoch": 1, "items_consumed": 0,
               "world": _world(dp=8, units=2, fp="aaaa")}
    with pytest.raises(ElasticWorldMismatchError, match="fingerprint"):
        resolve_resume(same_fp, policy="strict",
                       launched=_world(dp=8, units=2, fp="bbbb"))


# ---------------------------------------------------------------------------
# policy knob: validation, env overlay, finalize
# ---------------------------------------------------------------------------


def test_elastic_policy_knob_env_and_finalize(monkeypatch):
    from hydragnn_tpu.resilience.config import ResilienceConfig

    assert check_elastic_policy(None) == "strict"
    assert check_elastic_policy(" Epoch ") == "epoch"
    with pytest.raises(ValueError, match="elastic_resume"):
        check_elastic_policy("bogus")

    monkeypatch.delenv("HYDRAGNN_ELASTIC_RESUME", raising=False)
    assert elastic_policy_from_training({}) == "strict"
    assert elastic_policy_from_training({"elastic_resume": "epoch"}) == \
        "epoch"
    # env wins; set-but-empty falls through (the repo convention)
    monkeypatch.setenv("HYDRAGNN_ELASTIC_RESUME", "epoch")
    assert elastic_policy_from_training({}) == "epoch"
    assert ResilienceConfig.from_training({}).elastic_resume == "epoch"
    monkeypatch.setenv("HYDRAGNN_ELASTIC_RESUME", "")
    assert elastic_policy_from_training(
        {"elastic_resume": "epoch"}) == "epoch"
    assert ResilienceConfig.from_training({}).elastic_resume == "strict"
    monkeypatch.setenv("HYDRAGNN_ELASTIC_RESUME", "nope")
    with pytest.raises(ValueError):
        ResilienceConfig.from_training({})
    monkeypatch.delenv("HYDRAGNN_ELASTIC_RESUME")

    # config.finalize writes the default back and validates bad values
    from hydragnn_tpu.config.config import DatasetStats, finalize

    from tests.test_stream import _samples

    def _cfg_dict(**training):
        return {
            "Dataset": {},
            "NeuralNetwork": {
                "Architecture": {"model_type": "SAGE", "hidden_dim": 8,
                                 "num_conv_layers": 2,
                                 "output_heads": {"graph": {
                                     "num_sharedlayers": 1,
                                     "dim_sharedlayers": 8,
                                     "num_headlayers": 1,
                                     "dim_headlayers": [8]}}},
                "Variables_of_interest": {
                    "input_node_features": [0],
                    "output_names": ["e"], "output_index": [0],
                    "type": ["graph"], "output_dim": [1]},
                "Training": {"batch_size": 8, "num_epoch": 1,
                             "perc_train": 0.7, **training},
            },
        }

    stats = DatasetStats.from_samples(_samples(4))
    out = finalize(_cfg_dict(), stats)
    assert out["NeuralNetwork"]["Training"]["elastic_resume"] == "strict"
    out = finalize(_cfg_dict(elastic_resume="epoch"), stats)
    assert out["NeuralNetwork"]["Training"]["elastic_resume"] == "epoch"
    with pytest.raises(ValueError, match="elastic_resume"):
        finalize(_cfg_dict(elastic_resume="maybe"), stats)


# ---------------------------------------------------------------------------
# chaos knob + coordinator
# ---------------------------------------------------------------------------


def test_chaos_elastic_spec_parsing():
    assert _parse_elastic_spec("epoch:+1") == (None, 1)
    assert _parse_elastic_spec("epoch:-2") == (None, -2)
    assert _parse_elastic_spec("3:+1") == (3, 1)
    for bad in ("epoch", "2:0", "epoch:x", ":+1"):
        with pytest.raises(ValueError):
            _parse_elastic_spec(bad)


def test_chaos_elastic_arms_and_fires_once(monkeypatch):
    monkeypatch.setenv("HYDRAGNN_CHAOS_ELASTIC", "1:-1")
    chaos = Chaos.from_env()
    assert chaos is not None and chaos.elastic_armed
    assert chaos.elastic_now(0) == 0      # boundary before the pinned epoch
    assert chaos.elastic_now(1) == -1     # fires at the epoch-1 boundary
    assert chaos.elastic_now(2) == 0      # one-shot
    monkeypatch.delenv("HYDRAGNN_CHAOS_ELASTIC")
    assert Chaos.from_env() is None

    # config-section spelling
    chaos = Chaos.from_env({"elastic": "epoch:+2"})
    assert chaos.elastic_now(0) == 2


def test_coordinator_agreement_and_events(monkeypatch):
    monkeypatch.delenv("HYDRAGNN_CHAOS_ELASTIC", raising=False)
    # unarmed -> no coordinator at all (the common path carries nothing)
    assert ElasticCoordinator.from_env(chaos=None) is None
    assert ElasticCoordinator.from_env(chaos=Chaos(preempt_step=3)) is None

    tel = _Health()
    coord = ElasticCoordinator.from_env(
        chaos=Chaos(elastic_at=None, elastic_delta=-1), telemetry=tel,
        world_size=4)
    dec = coord.poll(epoch=0)
    assert dec == {"epoch": 1, "delta": -1, "world_size": 4,
                   "target_world_size": 3}
    assert coord.poll(epoch=1) is None  # fires once
    assert tel.kinds() == ["elastic_resize", "elastic_retire"]

    # a scheduler drain request (no chaos) grows the world; no retire
    tel2 = _Health()
    coord2 = ElasticCoordinator(telemetry=tel2, world_size=4)
    assert coord2.poll(epoch=0) is None
    coord2.request_resize(+2)
    dec = coord2.poll(epoch=1)
    assert dec["target_world_size"] == 6 and dec["epoch"] == 2
    assert tel2.kinds() == ["elastic_resize"]


# ---------------------------------------------------------------------------
# trainer end-to-end: resize, refuse, admit, trajectory parity
# ---------------------------------------------------------------------------

# constant global batch G=32 at every shape: 8-way mesh stacks 8 micro-
# batches of 4, a 4-device sub-mesh stacks 4 of 8, the local path takes
# one batch of 32 — so each dispatch unit covers the SAME 32-sample set
# and post-resize LOSS trajectories are comparable (FP-regroup tolerance).
# PARAM-level cross-layout parity needs a non-adaptive optimizer: Adam's
# elementwise normalization amplifies an FP-regroup difference in a
# near-zero gradient to a full lr-sized update of opposite sign, so only
# the SGD run below compares params across shapes.
_G = dict(n_train=64)
_RTOL = 5e-3


def _allclose_leaves(a, b, rtol=_RTOL, atol=5e-4):
    la = jax.tree_util.tree_leaves(jax.device_get(a))
    lb = jax.tree_util.tree_leaves(jax.device_get(b))
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        # atol floors the comparison for near-zero leaves, where regroup
        # noise is the same absolute size as the value itself
        np.testing.assert_allclose(np.asarray(x), np.asarray(y), rtol=rtol,
                                   atol=atol)


def test_trainer_elastic_resize_refuse_then_admit(tmp_path, monkeypatch):
    """Chaos arms a shrink at the epoch-0 boundary of an 8-way mesh run:
    the run exits with a world-stamped boundary bundle.  Relaunching on
    the local path (dp_extent 8 -> 1) is refused under strict and
    admitted under `epoch`, and the admitted trajectory matches an
    uninterrupted local run within FP-regroup tolerance."""
    monkeypatch.delenv("HYDRAGNN_CHAOS_ELASTIC", raising=False)
    loaders_mesh = _Loaders(**_G, batch_size=4)
    loaders_local = _Loaders(**_G, batch_size=32)

    state_a, hist_a = _run(loaders_local, tmp_path, "fixed", num_epoch=3)
    assert "preempted" not in hist_a

    monkeypatch.setenv("HYDRAGNN_CHAOS_ELASTIC", "epoch:-1")
    _, hist_b = _run(loaders_mesh, tmp_path, "resized", num_epoch=3,
                     use_mesh_dp=True)
    monkeypatch.delenv("HYDRAGNN_CHAOS_ELASTIC")
    assert hist_b.get("preempted") is True
    assert hist_b["elastic"]["delta"] == -1
    assert len(hist_b["train"]) == 1  # stopped at the epoch-0 boundary

    bundle = load_resume_bundle(
        _fresh_skeleton(loaders_local), resume_dir(str(tmp_path), "resized"))
    assert bundle is not None
    state_r, meta = bundle
    assert meta["epoch"] == 1 and meta["items_consumed"] == 0
    assert meta["reason"] == "elastic"
    assert meta["world"]["dp_extent"] == 8
    assert meta["world"]["epoch_units"] == 2

    # strict (the default) refuses the shape change LOUDLY
    with pytest.raises(ElasticWorldMismatchError, match="dp_extent=8"):
        _run(loaders_local, tmp_path, "resized", resume_meta=meta,
             state=state_r)

    # `epoch` admits: epochs 1-2 run at the new shape
    state_c, hist_c = _run(loaders_local, tmp_path, "resized",
                           resume_meta=meta, state=state_r,
                           training_extra={"elastic_resume": "epoch"})
    assert "preempted" not in hist_c
    assert len(hist_c["val"]) == 3  # mesh epoch 0 + admitted epochs 1-2
    np.testing.assert_allclose(hist_c["val"][1:], hist_a["val"][1:],
                               rtol=_RTOL)
    np.testing.assert_allclose(hist_c["train"][1:], hist_a["train"][1:],
                               rtol=_RTOL)


def test_trainer_elastic_submesh_zero_reshard_mid_epoch(tmp_path,
                                                       monkeypatch):
    """N=4 (explicit sub-mesh, ZeRO-1) preempted MID-epoch resumes at
    M=8 (full mesh, ZeRO-2) with an exact unit conversion — the
    consolidated bundle re-shards under the launched stage and the
    trajectory matches the uninterrupted 8-way run."""
    monkeypatch.delenv("HYDRAGNN_CHAOS_PREEMPT_STEP", raising=False)
    cfg, model = _model()
    from hydragnn_tpu.train.optimizer import select_optimizer
    from hydragnn_tpu.train.trainer import create_train_state

    loaders4 = _Loaders(**_G, batch_size=8)
    loaders8 = _Loaders(**_G, batch_size=4)

    def _mesh_run(loaders, name, extent, zero_stage, resume=None,
                  state=None, policy=None):
        # SGD: FP-regroup noise amplifies only LINEARLY across the resize,
        # so params stay comparable across layouts (see _RTOL note above)
        opt = select_optimizer({"type": "SGD", "learning_rate": 0.01})
        train_l, val_l, test_l = loaders()
        if state is None:
            state = create_train_state(model, next(iter(train_l)), opt)
        training = {"num_epoch": 3, "zero_stage": zero_stage}
        if policy:
            training["elastic_resume"] = policy
        mesh = (make_mesh(jax.devices()[:extent])
                if extent < N_DEV else None)
        return train_validate_test(
            model, cfg, state, opt, train_l, val_l, test_l,
            {"Training": training,
             "Variables_of_interest": {"output_names": ["e"]}},
            log_name=name, logs_dir=str(tmp_path), use_mesh_dp=True,
            mesh=mesh, resume_meta=resume)

    def _sgd_skeleton(loaders):
        opt = select_optimizer({"type": "SGD", "learning_rate": 0.01})
        train_l, _, _ = loaders()
        return create_train_state(model, next(iter(train_l)), opt)

    state_a, hist_a = _mesh_run(loaders8, "full8", 8, zero_stage=2)
    assert "preempted" not in hist_a

    # preempt the 4-device run after dispatch 3 = mid-epoch-1, 1 of 2 units
    monkeypatch.setenv("HYDRAGNN_CHAOS_PREEMPT_STEP", "3")
    _, hist_b = _mesh_run(loaders4, "sub4", 4, zero_stage=1)
    monkeypatch.delenv("HYDRAGNN_CHAOS_PREEMPT_STEP")
    assert hist_b.get("preempted") is True

    bundle = load_resume_bundle(
        _sgd_skeleton(loaders4), resume_dir(str(tmp_path), "sub4"))
    assert bundle is not None
    state_r, meta = bundle
    assert meta["epoch"] == 1 and meta["items_consumed"] == 1
    assert meta["world"]["dp_extent"] == 4
    assert meta["world"]["zero_stage"] == 1
    assert meta["pipeline"]["n_local_devices"] == 4  # sub-mesh stacking

    # admitted at 8 devices / ZeRO-2: 1 of 2 saved units converts to
    # exactly 1 of 2 launched units (G preserved) — no round-up
    state_c, hist_c = _mesh_run(loaders8, "sub4", 8, zero_stage=2,
                                resume=meta, state=state_r, policy="epoch")
    assert "preempted" not in hist_c
    np.testing.assert_allclose(hist_c["val"][1:], hist_a["val"][1:],
                               rtol=_RTOL)
    # params: COARSE same-basin/same-position check only.  The half epoch
    # trained pre-resize at the 4-device regroup can flip relu kinks
    # sitting within FP noise of zero, which genuinely changes a few
    # gradients (~1% on affected weights) — the tight assertions are the
    # val trajectory above and the bit-exact roundtrip/dormancy tests
    _allclose_leaves(state_c.params, state_a.params, rtol=3e-2, atol=5e-3)


def test_trainer_same_shape_resume_dormant_under_epoch_policy(tmp_path,
                                                              monkeypatch):
    """With Training.elastic_resume: epoch but an UNCHANGED world shape,
    a resumed run is bit-identical to the uninterrupted one — the
    elastic path is provably dormant on same-shape resumes."""
    monkeypatch.delenv("HYDRAGNN_CHAOS_PREEMPT_STEP", raising=False)
    loaders = _Loaders(n_train=32, batch_size=8)
    extra = {"elastic_resume": "epoch"}
    state_a, _ = _run(loaders, tmp_path, "base", training_extra=extra)

    monkeypatch.setenv("HYDRAGNN_CHAOS_PREEMPT_STEP", "6")
    _run(loaders, tmp_path, "cut", training_extra=extra)
    monkeypatch.delenv("HYDRAGNN_CHAOS_PREEMPT_STEP")

    bundle = load_resume_bundle(
        _fresh_skeleton(loaders), resume_dir(str(tmp_path), "cut"))
    assert bundle is not None
    state_r, meta = bundle
    assert meta["world"]["dp_extent"] == 1
    state_c, hist_c = _run(loaders, tmp_path, "cut", resume_meta=meta,
                           state=state_r, training_extra=extra)
    assert "preempted" not in hist_c
    assert _leaves_equal(state_c.params, state_a.params)
    assert _leaves_equal(state_c.opt_state, state_a.opt_state)


# ---------------------------------------------------------------------------
# stream open retry (satellite: flaky store opens)
# ---------------------------------------------------------------------------


def test_stream_open_retry_recorder_buffers_and_drains():
    from hydragnn_tpu.data.stream.config import (
        OpenRetryRecorder,
        pop_open_retries,
    )
    from hydragnn_tpu.resilience.ckpt_io import with_retries

    pop_open_retries()  # drain any prior state
    calls = {"n": 0}

    def flaky():
        calls["n"] += 1
        if calls["n"] < 3:
            raise OSError(f"flake {calls['n']}")

    assert with_retries(flaky, retries=2, backoff=0.0,
                        what="stream store open",
                        telemetry=OpenRetryRecorder())
    evs = pop_open_retries()
    assert [e["attempt"] for e in evs] == [1, 2]
    assert all(e["what"] == "stream store open" for e in evs)
    assert "flake 1" in evs[0]["error"]
    assert pop_open_retries() == []  # drained


def test_stream_open_retries_knob_and_flaky_open(tmp_path, monkeypatch):
    """An open that flakes transiently is retried (stream_open_retry
    events buffer for the trainer) and still serves streaming; an open
    that keeps failing exhausts the bounded attempts and falls back to
    the in-memory path with the attempt count in the reason."""
    import hydragnn_tpu.data.gpack as gpack_mod
    from hydragnn_tpu.data.gpack import GpackWriter
    from hydragnn_tpu.data.load_data import _stream_loading_and_splitting
    from hydragnn_tpu.data.stream.config import (
        StreamConfig,
        pop_fallback,
        pop_open_retries,
    )

    from tests.test_stream import _samples

    # knob: config key + env override + validation
    cfg = StreamConfig.from_dataset(
        {"stream": True, "stream_path": "/a", "stream_open_retries": 0})
    assert cfg.open_retries == 0
    monkeypatch.setenv("HYDRAGNN_STREAM_OPEN_RETRIES", "5")
    assert StreamConfig.from_dataset(
        {"stream": True, "stream_path": "/a"}).open_retries == 5
    monkeypatch.delenv("HYDRAGNN_STREAM_OPEN_RETRIES")
    with pytest.raises(ValueError, match="stream_open_retries"):
        StreamConfig.from_dataset(
            {"stream": True, "stream_path": "/a",
             "stream_open_retries": -1})

    path = GpackWriter(str(tmp_path / "s.gpack")).save(_samples(20))
    config = {
        "Dataset": {"graph_features": {"name": ["e"], "dim": [1]},
                    "node_features": {"name": ["x"], "dim": [1]}},
        "NeuralNetwork": {
            "Architecture": {"model_type": "SAGE", "hidden_dim": 8,
                             "num_conv_layers": 2,
                             "output_heads": {"graph": {
                                 "num_sharedlayers": 1,
                                 "dim_sharedlayers": 8,
                                 "num_headlayers": 1,
                                 "dim_headlayers": [8]}}},
            "Variables_of_interest": {
                "input_node_features": [0],
                "output_names": ["e"], "output_index": [0],
                "type": ["graph"], "output_dim": [1]},
            "Training": {"batch_size": 4, "num_epoch": 1,
                         "perc_train": 0.5},
        },
    }
    real = gpack_mod.GpackDataset
    fails = {"n": 1}

    class _Flaky(real):
        def __init__(self, p):
            if fails["n"] > 0:
                fails["n"] -= 1
                raise OSError("stale NFS handle")
            super().__init__(p)

    pop_open_retries()
    pop_fallback()
    monkeypatch.setattr(gpack_mod, "GpackDataset", _Flaky)
    scfg = StreamConfig.from_dataset(
        {"stream": True, "stream_path": path, "stream_open_retries": 1,
         "stream_window": 8})
    out = _stream_loading_and_splitting(dict(config), scfg)
    assert out is not None  # one flake survived -> streaming serves
    evs = pop_open_retries()
    assert len(evs) == 1 and "stale NFS" in evs[0]["error"]
    assert pop_fallback() is None

    # persistent failure: bounded attempts, then the loud fallback
    fails["n"] = 10 ** 6
    assert _stream_loading_and_splitting(dict(config), scfg) is None
    assert len(pop_open_retries()) == 2  # both bounded attempts failed
    reason = pop_fallback()
    assert reason and "2 attempt(s)" in reason
