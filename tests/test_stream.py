"""Streaming data plane (hydragnn_tpu/data/stream/) — tier-1 contracts.

The load-bearing claims, each asserted here:

- StreamPlan is a pure function of (seed, epoch, rank): identical replay,
  and the rank shares partition the wrap-padded epoch exactly;
- the windowed loader's batch stream is BIT-IDENTICAL to the in-memory
  GraphDataLoader on the same seed — for any window size, because the
  window bounds residency, not order;
- residency really is bounded: peak decoded samples <= window + one
  in-flight batch, independent of dataset size;
- fast-forward (mid-epoch resume) yields exactly the uninterrupted
  epoch's surviving suffix;
- ingest segments are atomic: torn files are rejected loudly, growth is
  picked up between epochs;
- the gpack-backed halo feed produces bit-identical HaloBatches to the
  in-memory partitioner;
- split_dataset / DistDataset no longer materialize lazy datasets.
"""

import json
import os
import warnings

import numpy as np
import pytest

from hydragnn_tpu.data.dataloader import (
    GraphDataLoader,
    PadSpec,
    bucket_pad_specs,
    bucket_pad_specs_from_sizes,
    pad_spec_for,
)
from hydragnn_tpu.data.gpack import GpackDataset, GpackWriter
from hydragnn_tpu.data.stream.config import (
    StreamConfig,
    check_stream_flag,
    stream_dataset_defaults,
)
from hydragnn_tpu.data.stream.ingest import (
    IngestWriter,
    ingest_jsonl,
    open_tail_store,
    read_manifest,
)
from hydragnn_tpu.data.stream.loader import (
    StreamingGraphLoader,
    find_stream_loader,
    split_stream_indices,
    stats_from_store,
    try_fast_forward,
)
from hydragnn_tpu.data.stream.plan import StreamPlan
from hydragnn_tpu.graph.batch import GraphSample, HeadSpec
from hydragnn_tpu.graph.neighborlist import radius_graph


def _samples(n, n_nodes=12, seed=11):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        pos = rng.rand(n_nodes, 3).astype(np.float32) * 2.0
        x = rng.rand(n_nodes, 1).astype(np.float32)
        out.append(GraphSample(
            x=x, pos=pos, edge_index=radius_graph(pos, 1.2, n_nodes),
            graph_y=x.sum(keepdims=True)[0], node_y=x))
    return out


HEADS = [HeadSpec("e", "graph", 1)]


@pytest.fixture(scope="module")
def store_and_samples(tmp_path_factory):
    d = tmp_path_factory.mktemp("stream_store")
    samples = _samples(40)
    written = GpackWriter(str(d / "s.gpack")).save(samples)
    store = GpackDataset(written)
    yield store, samples
    store.close()


def _leaves_equal(a, b):
    import jax

    la = jax.tree_util.tree_leaves(a)
    lb = jax.tree_util.tree_leaves(b)
    assert len(la) == len(lb)
    for x, y in zip(la, lb):
        x, y = np.asarray(x), np.asarray(y)
        assert x.dtype == y.dtype
        assert np.array_equal(x, y)


# ---------------------------------------------------------------------------
# plan
# ---------------------------------------------------------------------------


def test_plan_deterministic_and_partitions_hosts():
    n, ws = 101, 4
    plans = [StreamPlan(n, seed=5, rank=r, world_size=ws) for r in range(ws)]
    for epoch in (0, 1, 7):
        shares = [p.epoch_order(epoch) for p in plans]
        # identical replay for the same (seed, epoch, rank)
        for r, p in enumerate(plans):
            assert np.array_equal(shares[r], p.epoch_order(epoch))
        # equal-length shares covering the wrap-padded epoch exactly
        total = -(-n // ws) * ws
        assert all(len(s) == total // ws for s in shares)
        joined = np.concatenate(shares)
        assert len(joined) == total
        assert set(joined.tolist()) == set(range(n))
    # different epochs shuffle differently
    p0 = plans[0]
    assert not np.array_equal(p0.epoch_order(0), p0.epoch_order(1))


def test_plan_modes():
    p = StreamPlan(50, seed=3, mode="sequential", shuffle=False)
    assert np.array_equal(p.epoch_order(4), np.arange(50))
    b = StreamPlan(50, seed=3, mode="block", block=16)
    order = b.epoch_order(2)
    assert np.array_equal(order, b.epoch_order(2))  # deterministic
    assert sorted(order.tolist()) == list(range(50))  # a permutation
    with pytest.raises(ValueError):
        StreamPlan(10, mode="bogus")


# ---------------------------------------------------------------------------
# windowed loader: parity, replay, fast-forward, bounded residency
# ---------------------------------------------------------------------------


def _stream_loader(store, n, bs, window, shuffle=True, pad=None):
    return StreamingGraphLoader(
        store, np.arange(n), HEADS, bs, window=window, shuffle=shuffle,
        seed=13, pad_specs=[pad] if pad else None)


def test_stream_matches_in_memory_bitexact(store_and_samples):
    store, samples = store_and_samples
    pad = pad_spec_for(samples, 8)
    mem = GraphDataLoader(samples, HEADS, 8, pad_spec=pad, shuffle=True,
                          seed=13)
    for window in (3, 8, 64):  # window < batch, == batch, >> dataset/bs
        st = _stream_loader(store, 40, 8, window, pad=pad)
        for epoch in (0, 2):
            mem.set_epoch(epoch)
            st.set_epoch(epoch)
            mb, sb = list(mem), list(st)
            assert len(mb) == len(sb) == len(st)
            for a, b in zip(mb, sb):
                _leaves_equal(a, b)


def test_replay_same_epoch_identical(store_and_samples):
    store, _ = store_and_samples
    st = _stream_loader(store, 40, 8, 6)
    st.set_epoch(1)
    first = list(st)
    second = list(st)  # re-iterating replays the same plan
    assert len(first) == len(second)
    for a, b in zip(first, second):
        _leaves_equal(a, b)


def test_fast_forward_matches_suffix(store_and_samples):
    store, _ = store_and_samples
    st = _stream_loader(store, 40, 8, 6)
    st.set_epoch(0)
    full = list(st)
    st.set_epoch(0)
    assert try_fast_forward(st, 2)
    tail = list(st)
    assert len(tail) == len(full) - 2
    for a, b in zip(full[2:], tail):
        _leaves_equal(a, b)
    # wrapped chains: the walker finds the base and scales by fan-in
    class Wrap:
        def __init__(self, loader):
            self.loader = loader
            self.n_devices = 2

    w = Wrap(st)
    assert find_stream_loader(w) is st
    st.set_epoch(0)
    assert try_fast_forward(w, 1)
    assert len(list(st)) == len(full) - 2  # 1 unit * fan-in 2
    assert not try_fast_forward(object(), 1)


def test_bounded_residency(store_and_samples):
    store, _ = store_and_samples
    bs, window = 4, 5
    st = _stream_loader(store, 40, bs, window)
    st.set_epoch(0)
    n_batches = sum(1 for _ in st)
    assert n_batches == 10
    # the bounded-memory contract: W + one in-flight batch, << dataset
    assert st.last_resident_peak <= window + bs
    assert st.last_resident_peak < 40


def test_streamed_training_loss_bitparity(store_and_samples, tmp_path):
    """One epoch of real training: streamed loader vs in-memory loader
    produce bit-identical loss trajectories (same model/opt/seed)."""
    from hydragnn_tpu.models.base import GraphHeadCfg, ModelConfig
    from hydragnn_tpu.models.create import create_model
    from hydragnn_tpu.train.optimizer import select_optimizer
    from hydragnn_tpu.train.trainer import create_train_state, train_validate_test

    store, samples = store_and_samples
    pad = pad_spec_for(samples, 8)
    cfg = ModelConfig(
        model_type="SAGE", input_dim=1, hidden_dim=8, output_dim=(1,),
        output_type=("graph",), graph_head=GraphHeadCfg(1, 8, 1, (8,)),
        node_head=None, task_weights=(1.0,), num_conv_layers=2)
    conf = {"Training": {"num_epoch": 1},
            "Variables_of_interest": {"output_names": ["e"]}}

    def _train(train_loader, val_loader, test_loader, name):
        model = create_model(cfg)
        opt = select_optimizer({"type": "AdamW", "learning_rate": 0.01})
        state = create_train_state(model, next(iter(train_loader)), opt)
        _, hist = train_validate_test(
            model, cfg, state, opt, train_loader, val_loader, test_loader,
            conf, log_name=name, verbosity=0, logs_dir=str(tmp_path),
            use_mesh_dp=False)
        return hist

    mk_mem = lambda lo, hi, sh: GraphDataLoader(  # noqa: E731
        samples[lo:hi], HEADS, 8, pad_spec=pad, shuffle=sh, seed=13)
    mk_st = lambda lo, hi, sh: StreamingGraphLoader(  # noqa: E731
        store, np.arange(lo, hi), HEADS, 8, window=6, shuffle=sh, seed=13,
        pad_specs=[pad])
    h_mem = _train(mk_mem(0, 24, True), mk_mem(24, 32, False),
                   mk_mem(32, 40, False), "mem")
    h_st = _train(mk_st(0, 24, True), mk_st(24, 32, False),
                  mk_st(32, 40, False), "stream")
    assert h_mem["train"] == h_st["train"]
    assert h_mem["val"] == h_st["val"]
    assert h_mem["test"] == h_st["test"]


# ---------------------------------------------------------------------------
# store-level stats, splits, bucket ladders from size arrays
# ---------------------------------------------------------------------------


def test_stats_from_store_matches_from_samples(store_and_samples):
    from hydragnn_tpu.config.config import DatasetStats

    store, samples = store_and_samples
    a = stats_from_store(store, need_deg=True)
    b = DatasetStats.from_samples(samples, need_deg=True)
    assert a.max_nodes == b.max_nodes
    assert a.max_edges == b.max_edges
    assert a.graph_size_variable == b.graph_size_variable
    assert a.pna_deg == b.pna_deg


def test_split_stream_indices_matches_split_dataset():
    n, perc = 40, 0.7
    tr, va, te = split_stream_indices(n, perc)
    data = list(range(n))
    n_train = int(perc * n)
    n_val = int(((1 - perc) / 2) * n)
    assert tr.tolist() == data[:n_train]
    assert va.tolist() == data[n_train:n_train + n_val]
    assert te.tolist() == data[n_train + n_val:]


def test_bucket_specs_from_sizes_match_sample_path():
    samples = _samples(30, seed=4)
    nodes = np.asarray([s.num_nodes for s in samples])
    edges = np.asarray([s.num_edges for s in samples])
    assert (bucket_pad_specs_from_sizes(nodes, edges, 8, n_buckets=3)
            == bucket_pad_specs(samples, 8, n_buckets=3))


# ---------------------------------------------------------------------------
# ingestion: atomic manifest, torn rejection, tail growth, JSONL
# ---------------------------------------------------------------------------


def test_ingest_manifest_atomic_and_torn_rejected(tmp_path):
    d = str(tmp_path / "ingest")
    w = IngestWriter(d, seal_every=4)
    for s in _samples(10, seed=3):
        w.add(s)
    w.close()
    segs = read_manifest(d)
    assert [s["n"] for s in segs] == [4, 4, 2]
    assert w.n_sealed == 10
    # every listed segment exists at exactly its recorded size
    for s in segs:
        assert os.path.getsize(os.path.join(d, s["file"])) == s["bytes"]
    # resume appends after the last sealed segment
    w2 = IngestWriter(d, seal_every=4)
    for s in _samples(4, seed=5):
        w2.add(s)
    w2.close()
    assert len(open_tail_store(d)) == 14
    # tear a segment: it must be excluded loudly, the rest still load
    victim = read_manifest(d)[1]
    with open(os.path.join(d, victim["file"]), "r+b") as f:
        f.truncate(victim["bytes"] - 8)
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        valid = read_manifest(d)
    assert len(valid) == 3
    assert any("torn" in str(r.message) for r in rec)
    assert len(open_tail_store(d)) == 10
    # an unknown manifest format must refuse, not misread
    with open(os.path.join(d, "manifest.json"), "w") as f:  # graftlint: disable=ROB002 (test deliberately plants a bad manifest)
        json.dump({"format": "v999", "segments": []}, f)
    with pytest.raises(ValueError):
        read_manifest(d)


def test_tail_mode_picks_up_growth(tmp_path):
    d = str(tmp_path / "tail")
    w = IngestWriter(d, seal_every=4)
    for s in _samples(8, seed=6):
        w.add(s)
    w.close()
    store = open_tail_store(d)
    st = StreamingGraphLoader(store, np.arange(8), HEADS, 4, window=4,
                              shuffle=False, tail_dir=d)
    st.set_epoch(0)
    assert sum(1 for _ in st) == 2
    # growth between epochs: the next set_epoch re-reads the manifest
    w2 = IngestWriter(d, seal_every=4)
    for s in _samples(4, seed=7):
        w2.add(s)
    w2.close()
    st.set_epoch(1)
    assert st.tail_grew == (8, 12)
    assert sum(1 for _ in st) == 3


def test_ingest_jsonl_tolerant(tmp_path):
    jl = tmp_path / "cap.jsonl"
    recs = [
        {"x": [[1.0]], "pos": [[0.0, 0.0, 0.0]]},
        {"request": {"x": [[2.0], [3.0]],
                     "pos": [[0, 0, 0], [1, 0, 0]],
                     "edge_index": [[0, 1], [1, 0]]}},
    ]
    jl.write_text("\n".join([json.dumps(recs[0]), "NOT JSON",
                             json.dumps(recs[1])]) + "\n")
    d = str(tmp_path / "out")
    with warnings.catch_warnings(record=True) as rec:
        warnings.simplefilter("always")
        n = ingest_jsonl(str(jl), d, seal_every=2)
    assert n == 2
    assert any("malformed" in str(r.message) for r in rec)
    store = open_tail_store(d)
    assert len(store) == 2
    assert store[1].edge_index.shape == (2, 2)


# ---------------------------------------------------------------------------
# disk-backed halo feed
# ---------------------------------------------------------------------------


def test_gpack_halo_bit_equality(tmp_path):
    from hydragnn_tpu.data.stream.halo import (
        GpackShardedLoader,
        sharded_from_stream,
    )
    from hydragnn_tpu.graph.partition import (
        GraphShardConfig,
        ShardedGraphLoader,
    )

    heads = [HeadSpec("charge", "node", 1)]
    rng = np.random.RandomState(7)
    samples = []
    for _ in range(3):
        pos = rng.rand(24, 3).astype(np.float32) * 2.0
        x = rng.rand(24, 1).astype(np.float32)
        samples.append(GraphSample(
            x=x, pos=pos, edge_index=radius_graph(pos, 1.0, 24), node_y=x))
    maxn = max(s.num_nodes for s in samples)
    maxe = max(s.num_edges for s in samples)
    pad = PadSpec(num_nodes=maxn + 8, num_edges=maxe + 8, num_graphs=2)
    cfg = GraphShardConfig(backend="halo", method="sfc", hops=0, halo_max=0)

    mem = GraphDataLoader(samples, heads, 1, pad_spec=pad, shuffle=False)
    ref = ShardedGraphLoader(mem, 4, cfg, 2, ["node"])
    written = GpackWriter(str(tmp_path / "h.gpack")).save(samples)
    store = GpackDataset(written)
    gp = GpackShardedLoader(store, np.arange(3), 4, cfg, 2, heads,
                            num_graphs=2)
    ra, rb = list(ref), list(gp)
    assert len(ra) == len(rb) == 3
    for a, b in zip(ra, rb):
        _leaves_equal(a, b)
    assert gp.peek_stats()["n_shards"] == 4

    # sharded_from_stream only qualifies batch_size==1 single-host chains
    st1 = StreamingGraphLoader(store, np.arange(3), heads, 1,
                               pad_specs=[pad])
    assert sharded_from_stream(st1, 4, cfg, 2) is not None
    st2 = StreamingGraphLoader(store, np.arange(3), heads, 2,
                               pad_specs=[pad])
    assert sharded_from_stream(st2, 4, cfg, 2) is None
    store.close()


# ---------------------------------------------------------------------------
# config plumbing
# ---------------------------------------------------------------------------


def test_stream_config_spellings_and_env(monkeypatch):
    assert check_stream_flag(True) and check_stream_flag("on")
    assert not check_stream_flag(None) and not check_stream_flag("off")
    with pytest.raises(ValueError):
        check_stream_flag("maybe")
    cfg = StreamConfig.from_dataset({"stream": True, "stream_path": "/a",
                                     "stream_window": 7})
    assert cfg.enabled and cfg.path == "/a" and cfg.window == 7
    monkeypatch.setenv("HYDRAGNN_STREAM_WINDOW", "9")
    monkeypatch.setenv("HYDRAGNN_STREAM_ORDER", "block")
    cfg = StreamConfig.from_dataset({"stream": True, "stream_path": "/a"})
    assert cfg.window == 9 and cfg.order == "block"
    # tail implies enabled
    cfg = StreamConfig.from_dataset({"stream_tail": "/cap"})
    assert cfg.enabled and cfg.tail == "/cap"
    monkeypatch.delenv("HYDRAGNN_STREAM_WINDOW")
    with pytest.raises(ValueError):
        StreamConfig.from_dataset({"stream": True, "stream_window": 0})


def test_finalize_writes_stream_defaults(store_and_samples):
    from hydragnn_tpu.config.config import DatasetStats, finalize

    _, samples = store_and_samples
    stats = DatasetStats.from_samples(samples)
    config = {
        "Dataset": {},
        "NeuralNetwork": {
            "Architecture": {"model_type": "SAGE", "hidden_dim": 8,
                             "num_conv_layers": 2,
                             "output_heads": {"graph": {
                                 "num_sharedlayers": 1,
                                 "dim_sharedlayers": 8,
                                 "num_headlayers": 1,
                                 "dim_headlayers": [8]}}},
            "Variables_of_interest": {
                "input_node_features": [0],
                "output_names": ["e"], "output_index": [0],
                "type": ["graph"], "output_dim": [1]},
            "Training": {"batch_size": 8, "num_epoch": 1,
                         "perc_train": 0.7},
        },
    }
    out = finalize(config, stats)
    ds = out["Dataset"]
    for k, v in stream_dataset_defaults().items():
        assert k in ds, k
    assert ds["stream"] is False


# ---------------------------------------------------------------------------
# lazy splitting / no-materialize satellites
# ---------------------------------------------------------------------------


class _CountingDataset:
    """Sequence that counts item decodes — materialization detector."""

    def __init__(self, n):
        self.n = n
        self.gets = 0

    def __len__(self):
        return self.n

    def __getitem__(self, i):
        if isinstance(i, (int, np.integer)):
            self.gets += 1
            return int(i)
        raise TypeError(i)


def test_split_dataset_lazy_no_materialize():
    from hydragnn_tpu.data.splitting import IndexedSubset, split_dataset

    ds = _CountingDataset(40)
    tr, va, te = split_dataset(ds, 0.7)
    assert ds.gets == 0  # splitting decoded NOTHING
    assert isinstance(tr, IndexedSubset)
    assert len(tr) == 28 and len(va) == 6 and len(te) == 6
    assert tr[0] == 0 and va[0] == 28 and te[-1] == 39
    assert ds.gets == 3
    # list inputs keep returning plain list slices
    tr2, va2, te2 = split_dataset(list(range(40)), 0.7)
    assert isinstance(tr2, list) and tr2 == list(range(28))
    assert [len(va2), len(te2)] == [6, 6]


def test_numpy_part_mmap_close(tmp_path):
    samples = _samples(5, seed=9)
    written = GpackWriter(str(tmp_path / "m.gpack")).save(samples)
    store = GpackDataset(written, use_native=False)
    s0 = store[0]
    assert np.array_equal(s0.x, samples[0].x)
    view = store.sample_view(2, "x")  # zero-copy view over the mmap
    assert np.array_equal(view, samples[2].x)
    nodes, edges = store.sizes()
    assert nodes.tolist() == [s.num_nodes for s in samples]
    assert edges.tolist() == [s.num_edges for s in samples]
    store.close()  # live view exported — close must not raise
    assert np.array_equal(np.asarray(view), samples[2].x)
