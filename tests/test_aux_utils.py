"""Unit tests for aux subsystems: SMILES parsing, atomic descriptors,
visualizer, SLURM nodelist parsing, orbax checkpointing, profiler schedule,
timers (parity with reference tests/test_atomicdescriptors.py and the aux
subsystem inventory in SURVEY.md §5)."""

import os

import numpy as np
import pytest


def test_smiles_parser_basic():
    from hydragnn_tpu.utils.smiles_utils import generate_graphdata_from_smilestr

    # ethanol: 3 heavy atoms, 2 bonds -> 4 directed edges
    g = generate_graphdata_from_smilestr("CCO", 1.23)
    assert g.num_nodes == 3
    assert g.num_edges == 4
    assert g.graph_y[0] == pytest.approx(1.23)

    # benzene: aromatic ring, 6 atoms, 6 ring bonds -> 12 directed edges
    g = generate_graphdata_from_smilestr("c1ccccc1", 0.0)
    assert g.num_nodes == 6
    assert g.num_edges == 12
    # aromatic flag set on every atom
    assert (g.x[:, 10] == 1.0).all()

    # branches and double bonds: acetic acid CC(=O)O
    g = generate_graphdata_from_smilestr("CC(=O)O", 0.0)
    assert g.num_nodes == 4
    assert g.num_edges == 6


def test_atomicdescriptors():
    from hydragnn_tpu.utils.atomicdescriptors import (
        atomicdescriptors,
        group_period,
    )

    assert group_period(1) == (1, 1)
    assert group_period(6) == (14, 2)
    assert group_period(8) == (16, 2)
    assert group_period(26) == (8, 4)

    ad = atomicdescriptors(element_types=["C", "H", "O"])
    f = ad.get_atom_features(6)
    assert f.shape[0] == 6
    assert np.all(f >= 0) and np.all(f <= 1)

    ad1h = atomicdescriptors(element_types=["C", "H", "O"], one_hot=True)
    f = ad1h.get_atom_features(8)
    assert f.shape[0] == 9  # 3 one-hot + 6 properties


def test_visualizer(tmp_path):
    from hydragnn_tpu.postprocess.visualizer import Visualizer

    v = Visualizer("viztest", num_heads=2, logs_dir=str(tmp_path))
    rng = np.random.RandomState(0)
    t = [rng.rand(50, 1), rng.rand(50, 1)]
    p = [x + 0.05 * rng.randn(50, 1) for x in t]
    v.create_scatter_plots(t, p, ["a", "b"])
    v.create_error_histograms(t, p)
    v.plot_history({"train": [1.0, 0.5], "val": [1.1, 0.6], "test": [1.2, 0.7]})
    v.num_nodes_plot([4, 8, 8, 2])
    out = os.listdir(os.path.join(str(tmp_path), "viztest"))
    assert {"scatter.png", "error_pdf.png", "history.png",
            "num_nodes.png"} <= set(out)


def test_visualizer_global_analysis(tmp_path):
    """Cond-mean + error-PDF global analysis and per-component vector parity
    (reference visualizer.py:134-279, 467-613)."""
    from hydragnn_tpu.postprocess.visualizer import Visualizer

    v = Visualizer("viztest2", num_heads=2, head_dims=[1, 3],
                   logs_dir=str(tmp_path))
    rng = np.random.RandomState(1)
    t_scalar = rng.rand(80, 1)
    p_scalar = t_scalar + 0.1 * rng.randn(80, 1)
    t_vec = rng.rand(60, 3)
    p_vec = t_vec + 0.05 * rng.randn(60, 3)
    v.create_plot_global_analysis("energy", t_scalar, p_scalar)
    v.create_plot_global_analysis("forces", t_vec, p_vec)
    v.create_parity_plot_vector("forces", t_vec, p_vec, 3)
    out = os.listdir(os.path.join(str(tmp_path), "viztest2"))
    assert {"global_analysis_energy.png", "global_analysis_forces.png",
            "parity_vector_forces.png"} <= set(out)

    # cond-mean helper: binned error means track the injected error scale
    xs, em = Visualizer._err_condmean(t_scalar, p_scalar)
    assert xs.shape == em.shape and len(xs) > 5
    assert 0.02 < em.mean() < 0.3


def test_visualizer_per_node_and_scalar_panels(tmp_path):
    """Remaining reference plot types: scalar parity+error-PDF combo,
    per-node error PDFs, per-node vector parity, and the all-heads global
    driver (reference visualizer.py:281-466, 519-613, 722-733)."""
    from hydragnn_tpu.postprocess.visualizer import Visualizer

    v = Visualizer("viztest3", num_heads=2, logs_dir=str(tmp_path))
    rng = np.random.RandomState(2)
    t_scalar = rng.rand(80, 1)
    p_scalar = t_scalar + 0.1 * rng.randn(80, 1)
    # fixed-size graphs: [num_samples, num_nodes] node scalars and
    # [num_samples, num_nodes*3] node vectors
    t_node = rng.rand(40, 6)
    p_node = t_node + 0.05 * rng.randn(40, 6)
    t_nvec = rng.rand(40, 6 * 3)
    p_nvec = t_nvec + 0.05 * rng.randn(40, 6 * 3)

    v.create_parity_plot_and_error_histogram_scalar("e", t_scalar, p_scalar)
    v.create_error_histogram_per_node("q", t_node, p_node)
    v.create_error_histogram_per_node("e", t_scalar, p_scalar)  # skipped
    v.create_parity_plot_per_node_vector("f", t_nvec, p_nvec)
    v.create_plot_global([t_scalar, t_node], [p_scalar, p_node], ["e", "q"])

    out = set(os.listdir(os.path.join(str(tmp_path), "viztest3")))
    assert {"parity_errpdf_e.png", "errpdf_per_node_q.png",
            "parity_per_node_f.png", "global_analysis_e.png",
            "global_analysis_q.png"} <= out
    assert "errpdf_per_node_e.png" not in out


def test_slurm_nodelist_parsing():
    from hydragnn_tpu.utils.slurm import parse_slurm_nodelist

    assert parse_slurm_nodelist("frontier[00001-00003]") == [
        "frontier00001", "frontier00002", "frontier00003"]
    assert parse_slurm_nodelist("node1,node2") == ["node1", "node2"]
    assert parse_slurm_nodelist("n[1,5-6]") == ["n1", "n5", "n6"]


def test_orbax_checkpoint_roundtrip(tmp_path):
    import jax.numpy as jnp

    from hydragnn_tpu.train.trainer import TrainState
    from hydragnn_tpu.utils.checkpoint import (
        latest_step,
        restore_checkpoint,
        save_checkpoint,
    )

    state = TrainState(
        step=jnp.asarray(7),
        params={"w": jnp.arange(4.0)},
        batch_stats={"bn": {"mean": jnp.ones(3)}},
        opt_state={"m": jnp.zeros(4)},
    )
    d = str(tmp_path / "ckpt")
    save_checkpoint(state, d)
    assert latest_step(d) == 7
    skeleton = TrainState(
        step=jnp.asarray(0),
        params={"w": jnp.zeros(4)},
        batch_stats={"bn": {"mean": jnp.zeros(3)}},
        opt_state={"m": jnp.ones(4)},
    )
    restored = restore_checkpoint(skeleton, d)
    assert int(restored.step) == 7
    np.testing.assert_array_equal(np.asarray(restored.params["w"]),
                                  np.arange(4.0))


def test_profiler_schedule(tmp_path, monkeypatch):
    from hydragnn_tpu.utils import profile as prof

    calls = []
    monkeypatch.setattr(
        "jax.profiler.start_trace", lambda d: calls.append(("start", d)))
    monkeypatch.setattr(
        "jax.profiler.stop_trace", lambda: calls.append(("stop",)))
    p = prof.Profiler({"enable": 1, "wait": 2, "warmup": 1, "active": 2,
                       "trace_dir": str(tmp_path / "tr")})
    for _ in range(10):
        p.step()
    assert [c[0] for c in calls] == ["start", "stop"]


def test_timers():
    from hydragnn_tpu.utils.time_utils import Timer, get_timer, reset_timers

    reset_timers()
    with Timer("region_a"):
        pass
    t = get_timer("region_a")
    assert t.count == 1 and t.total >= 0.0
