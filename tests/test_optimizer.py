"""Smoke matrix over optimizer types (parity: reference
tests/test_optimizer.py:21-23)."""

import json
import os

import pytest

import hydragnn_tpu
from test_graphs import _generate_data

OPTIMIZERS = ["SGD", "Adam", "Adadelta", "Adagrad", "Adamax", "AdamW",
              "RMSprop", "FusedLAMB"]


@pytest.mark.parametrize("opt_type", OPTIMIZERS)
@pytest.mark.parametrize("use_zero", [False, True])
def test_optimizers(opt_type, use_zero):
    with open(os.path.join(os.path.dirname(__file__), "inputs", "ci.json")) as f:
        config = json.load(f)
    config["NeuralNetwork"]["Architecture"]["model_type"] = "SAGE"
    config["NeuralNetwork"]["Training"]["num_epoch"] = 2
    config["NeuralNetwork"]["Training"]["Optimizer"]["type"] = opt_type
    config["NeuralNetwork"]["Training"]["Optimizer"]["use_zero_redundancy"] = use_zero
    _generate_data(config, num_samples_tot=60)
    if use_zero and opt_type == "FusedLAMB":
        # ZeRO + a per-tensor optimizer is REFUSED at config time: LAMB's
        # trust ratio would silently change under slice partitioning
        # (parallel/zero.py, docs/SCALING.md §4)
        with pytest.raises(ValueError, match="elementwise"):
            hydragnn_tpu.run_training(config)
        return
    hydragnn_tpu.run_training(config)


def test_unknown_optimizer_raises():
    from hydragnn_tpu.train.optimizer import select_optimizer

    with pytest.raises(NameError):
        select_optimizer({"type": "NotAnOptimizer", "learning_rate": 1e-3})
