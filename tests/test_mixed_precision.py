"""Mixed precision (Architecture.mixed_precision -> bf16 compute): params,
gradients, loss, and batch statistics stay f32 while the forward computes in
bfloat16 — cast at the train-step boundary, no per-layer dtype plumbing."""

import dataclasses

import numpy as np

import jax
import jax.numpy as jnp

from hydragnn_tpu.graph.batch import GraphSample, HeadSpec, PadSpec, collate
from hydragnn_tpu.graph.neighborlist import radius_graph
from hydragnn_tpu.models.base import GraphHeadCfg, ModelConfig
from hydragnn_tpu.models.create import create_model
from hydragnn_tpu.train.optimizer import select_optimizer
from hydragnn_tpu.train.trainer import create_train_state, make_train_step


def _setup(model_type="SchNet"):
    rng = np.random.RandomState(0)
    samples = []
    for _ in range(8):
        pos = rng.rand(10, 3).astype(np.float32) * 2.5
        x = rng.randint(0, 4, (10, 1)).astype(np.float32)
        ei = radius_graph(pos, 1.3, max_neighbours=8)
        samples.append(GraphSample(
            x=x, pos=pos, edge_index=ei,
            graph_y=rng.rand(1).astype(np.float32)))
    batch = collate(samples, PadSpec.for_batch(8, 12, 60),
                    [HeadSpec("e", "graph", 1)])
    cfg = ModelConfig(
        model_type=model_type, input_dim=1, hidden_dim=16,
        output_dim=(1,), output_type=("graph",),
        graph_head=GraphHeadCfg(1, 16, 1, (16,)), node_head=None,
        task_weights=(1.0,), num_conv_layers=2, num_gaussians=8,
        num_filters=16, radius=1.3, max_neighbours=8)
    return cfg, batch


def test_bf16_step_matches_f32_within_tolerance():
    cfg, batch = _setup()
    model = create_model(cfg)
    opt = select_optimizer({"type": "AdamW", "learning_rate": 1e-3})
    state = create_train_state(model, batch, opt)

    losses = {}
    for dt in ("float32", "bfloat16"):
        cfg_dt = dataclasses.replace(cfg, compute_dtype=dt)
        step = jax.jit(  # graftlint: disable=TRC003 (one jit per dtype config by design; 2 iterations)
            make_train_step(create_model(cfg_dt), cfg_dt, opt))
        new_state, metrics = step(state, batch)
        losses[dt] = float(metrics["loss"])
        # params, grads-updated params, and batch stats remain f32
        for leaf in jax.tree.leaves(new_state.params):
            assert leaf.dtype == jnp.float32
        for leaf in jax.tree.leaves(new_state.batch_stats):
            assert leaf.dtype == jnp.float32
    assert np.isfinite(losses["bfloat16"])
    assert abs(losses["bfloat16"] - losses["float32"]) < 0.05 * (
        abs(losses["float32"]) + 1e-3)


def test_bf16_training_decreases_loss():
    cfg, batch = _setup("SAGE")
    cfg = dataclasses.replace(cfg, compute_dtype="bfloat16")
    model = create_model(cfg)
    opt = select_optimizer({"type": "AdamW", "learning_rate": 5e-3})
    state = create_train_state(model, batch, opt)
    step = jax.jit(make_train_step(model, cfg, opt))
    first = None
    for _ in range(30):
        state, metrics = step(state, batch)
        if first is None:
            first = float(metrics["loss"])
    last = float(metrics["loss"])
    assert np.isfinite(last) and last < first


def test_bf16_dimenet_triplet_chain():
    """DimeNet under bf16: the basis outputs are cast to the compute dtype
    (models/dimenet.py DimeNetConv) so the [T, *] triplet streams — the
    step's dominant HBM traffic — run in bf16 instead of promoting back to
    f32 through the f32 basis/mask operands.  Loss must stay within bf16
    tolerance of the f32 step and training must still converge."""
    from hydragnn_tpu.models.dimenet import add_dimenet_extras, count_triplets

    cfg, batch = _setup("DimeNet")
    cfg = dataclasses.replace(
        cfg, envelope_exponent=5, num_before_skip=1, num_after_skip=1,
        num_radial=4, num_spherical=3, basis_emb_size=4, int_emb_size=16,
        out_emb_size=16)
    real = np.asarray(batch.edge_mask) > 0
    ei = np.stack([np.asarray(batch.senders)[real],
                   np.asarray(batch.receivers)[real]])
    t = count_triplets(ei, batch.x.shape[0])
    batch = add_dimenet_extras(batch, max_triplets=t + 4)
    batch = jax.device_put(batch)

    model = create_model(cfg)
    opt = select_optimizer({"type": "AdamW", "learning_rate": 1e-3})
    state = create_train_state(model, batch, opt)
    losses = {}
    for dt in ("float32", "bfloat16"):
        cfg_dt = dataclasses.replace(cfg, compute_dtype=dt)
        step = jax.jit(  # graftlint: disable=TRC003 (one jit per dtype config by design; 2 iterations)
            make_train_step(create_model(cfg_dt), cfg_dt, opt))
        s = state
        for _ in range(10):
            s, metrics = step(s, batch)
        losses[dt] = float(metrics["loss"])
        assert np.isfinite(losses[dt])
    assert abs(losses["bfloat16"] - losses["float32"]) < 0.1 * (
        abs(losses["float32"]) + 1e-3)


def test_mixed_precision_config_key():
    arch = {
        "model_type": "SAGE", "input_dim": 1, "hidden_dim": 8,
        "output_dim": [1], "output_type": ["graph"],
        "output_heads": {"graph": {"num_sharedlayers": 1,
                                   "dim_sharedlayers": 8,
                                   "num_headlayers": 1,
                                   "dim_headlayers": [8]}},
        "task_weights": [1.0], "num_conv_layers": 2,
        "mixed_precision": True,
    }
    cfg = ModelConfig.from_config(
        {"Architecture": arch, "Training": {},
         "Variables_of_interest": {}})
    assert cfg.compute_dtype == "bfloat16"

    import pytest

    bad = dict(arch, mixed_precision=False, compute_dtype="fp16")
    with pytest.raises(ValueError, match="compute_dtype"):
        ModelConfig.from_config(
            {"Architecture": bad, "Training": {},
             "Variables_of_interest": {}})
