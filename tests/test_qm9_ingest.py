"""Real-QM9 ingest path: load_qm9_xyz must parse the exact gdb9 .xyz layout
(count line; property line ``gdb <id> A B C mu alpha homo lumo gap r2 zpve
U0 U H G Cv``; atom rows ``El x y z mulliken`` with Fortran ``*^``
exponents; frequency/SMILES/InChI trailer lines) so a user who stages the
real archive gets real-data training with the reference's target (free
energy G; reference examples/qm9/qm9.py:15-22).  The archive itself cannot
be downloaded in this environment — this fixture is two molecules written
by hand IN the gdb9 layout (water-like and methane-like geometries), which
validates the wiring, not chemistry."""

import importlib.util
import os

import numpy as np

# load the example driver under a unique module name — a sys.path insert
# would claim the generic name 'train' for the whole pytest session
_spec = importlib.util.spec_from_file_location(
    "qm9_example_train",
    os.path.join(os.path.dirname(os.path.dirname(os.path.abspath(__file__))),
                 "examples", "qm9", "train.py"))
_qm9_train = importlib.util.module_from_spec(_spec)
_spec.loader.exec_module(_qm9_train)

# two hand-written files in the exact gdb9 layout
_WATER = """3
gdb 1\t157.7 157.7 157.7 1.85 6.3 -0.25 0.01 0.26 35.4 0.021 -76.4 -76.39 -76.38 -76.41 6.0
O\t0.0\t0.0\t0.1173*^-1\t-0.6
H\t0.0\t0.7572\t-0.4692\t0.3
H\t0.0\t-0.7572\t-0.4692\t0.3
1595.2 3657.1 3755.9
O\tO
InChI=1S/H2O/h1H2\tInChI=1S/H2O/h1H2
"""

_METHANE = """5
gdb 2\t157.7 157.7 157.7 0.0 11.8 -0.38 0.07 0.45 29.9 0.044 -40.5 -40.49 -40.48 -40.51 7.5
C\t0.0\t0.0\t0.0\t-0.4
H\t0.629\t0.629\t0.629\t0.1
H\t-0.629\t-0.629\t0.629\t0.1
H\t-0.629\t0.629\t-0.629\t0.1
H\t0.629\t-0.629\t-0.629\t0.1
1306.2 1534.1 2917.0 3019.5
C\tC
InChI=1S/CH4/h1H4\tInChI=1S/CH4/h1H4
"""


def test_load_qm9_xyz_gdb9_layout(tmp_path):
    load_qm9_xyz = _qm9_train.load_qm9_xyz

    (tmp_path / "dsgdb9nsd_000001.xyz").write_text(_WATER)
    (tmp_path / "dsgdb9nsd_000002.xyz").write_text(_METHANE)
    samples = load_qm9_xyz(str(tmp_path), radius=2.0)
    assert len(samples) == 2

    water, methane = samples
    # atomic numbers parsed from element symbols
    np.testing.assert_array_equal(water.x.ravel(), [8, 1, 1])
    np.testing.assert_array_equal(methane.x.ravel(), [6, 1, 1, 1, 1])
    # Fortran-style exponent handled: 0.1173*^-1 == 0.01173
    assert abs(water.pos[0, 2] - 0.01173) < 1e-9
    # target = free energy G (token 15) per atom, standardized across the set
    g = np.asarray([-76.41 / 3, -40.51 / 5])
    expect = (g - g.mean()) / g.std()
    got = np.asarray([water.graph_y[0], methane.graph_y[0]])
    np.testing.assert_allclose(got, expect, rtol=1e-5)
    # O-H bonds inside the 2.0 A radius graph
    assert water.edge_index.shape[1] >= 4
