"""Fused multi-aggregator kernel (ops/poly_mp.py): forward and gradient
parity vs the composed XLA path — f32, masked/padded edges, multi-graph
batches, tie handling — plus the graph/segment.py dispatchers' fallback
equivalence and the trace-time dispatch tally.  Interpret mode on CPU,
same collate invariants as production.  (Model-level parity for every
routed arch — PNA, MFC, CGCNN, SAGE — lives in tests/test_fused_mp.py's
canonical-arch-list parametrization, which exercises this kernel under
HYDRAGNN_AGGR_BACKEND=fused.)"""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from hydragnn_tpu.graph import segment
from hydragnn_tpu.graph.batch import GraphSample, HeadSpec, PadSpec, collate
from hydragnn_tpu.graph.neighborlist import radius_graph
from hydragnn_tpu.ops.poly_mp import (
    gather_poly_segment,
    segment_poly_dense,
)

_BIG = 1e9
ALL_MOMENTS = ("sum", "sq", "mxmn", "cnt")


def _batch(n_graphs=24, max_nodes=16, seed=0, max_neigh=10):
    rng = np.random.RandomState(seed)
    samples = []
    for _ in range(n_graphs):
        n = int(rng.randint(3, max_nodes + 1))
        pos = rng.rand(n, 3).astype(np.float32) * 2.5
        x = rng.rand(n, 2).astype(np.float32)
        ei = radius_graph(pos, 1.4, max_neigh)
        samples.append(GraphSample(x=x, pos=pos, edge_index=ei,
                                   graph_y=np.ones(1, np.float32), node_y=x))
    pad = PadSpec.for_batch(n_graphs, max_nodes, max_nodes * max_neigh)
    return collate(samples, pad, [HeadSpec("e", "graph", 1)])


def _edge_data(b, f=48, seed=1, quantize=False):
    rng = np.random.RandomState(seed)
    e = b.senders.shape[0]
    data = rng.randn(e, f).astype(np.float32)
    if quantize:
        # coarse grid -> deliberate within-segment ties, exercising the
        # even tie-split of the max/min gradient
        data = np.round(data * 2.0) / 2.0
    return jnp.asarray(data)


def _refs(data, ids, mask, n):
    """Composed-path moments with the production masking conventions."""
    dm = data * mask[:, None]
    cat = jnp.concatenate([data, -data], axis=1)
    cat = jnp.where(mask[:, None] > 0, cat, -_BIG)
    mxmn = jax.ops.segment_max(cat, ids, num_segments=n)
    return {
        "sum": jax.ops.segment_sum(dm, ids, num_segments=n),
        "sq": jax.ops.segment_sum(dm * dm, ids, num_segments=n),
        "mxmn": mxmn,
        "cnt": jax.ops.segment_sum(mask, ids, num_segments=n),
    }


def test_scatter_forward_all_moments():
    b = _batch()
    data = _edge_data(b)
    ids, mask = jnp.asarray(b.receivers), jnp.asarray(b.edge_mask)
    n = b.x.shape[0]
    outs = segment_poly_dense(data, ids, n, ALL_MOMENTS, valid=mask)
    ref = _refs(data, ids, mask, n)
    np.testing.assert_allclose(outs[0], ref["sum"], rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(outs[1], ref["sq"], rtol=1e-5, atol=1e-5)
    # empty segments: kernel yields -1e9, XLA's masked max too (both
    # pre-clean) — compare after the common clamp
    np.testing.assert_allclose(
        jnp.where(outs[2] <= -_BIG * 0.5, -_BIG, outs[2]),
        jnp.where(ref["mxmn"] <= -_BIG * 0.5, -_BIG, ref["mxmn"]),
        rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(outs[3], ref["cnt"], rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("quantize", [False, True],
                         ids=["distinct", "with-ties"])
def test_scatter_gradients_match_composed(quantize):
    """d(sum)/d(sq)/d(max)/d(min) vs the composed twin, including the
    even tie split jax.ops.segment_max's VJP applies."""
    b = _batch(seed=2)
    data = _edge_data(b, seed=3, quantize=quantize)
    ids, mask = jnp.asarray(b.receivers), jnp.asarray(b.edge_mask)
    n = b.x.shape[0]
    f = data.shape[1]

    def loss_fused(d):
        s, q, mxmn, cnt = segment_poly_dense(d, ids, n, ALL_MOMENTS,
                                             valid=mask)
        mx = jnp.where(mxmn[:, :f] <= -_BIG * 0.5, 0.0, mxmn[:, :f])
        mn = jnp.where(mxmn[:, f:] <= -_BIG * 0.5, 0.0, -mxmn[:, f:])
        return (jnp.sum(s ** 2) + 0.5 * jnp.sum(q ** 2)
                + jnp.sum(mx ** 2) + jnp.sum(mn ** 3) + jnp.sum(cnt))

    def loss_ref(d):
        r = _refs(d, ids, mask, n)
        mm = jnp.where(r["mxmn"] <= -_BIG * 0.5, 0.0, r["mxmn"])
        return (jnp.sum(r["sum"] ** 2) + 0.5 * jnp.sum(r["sq"] ** 2)
                + jnp.sum(mm[:, :f] ** 2) + jnp.sum((-mm[:, f:]) ** 3)
                + jnp.sum(r["cnt"]))

    g1 = jax.grad(loss_fused)(data)
    g2 = jax.grad(loss_ref)(data)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-4, atol=1e-4)
    # masked edges must carry EXACTLY zero gradient
    m = np.asarray(b.edge_mask)
    assert np.all(np.asarray(g1)[m == 0] == 0.0)


def test_gather_forward_and_gradients():
    """Gather mode (messages formed in-VMEM): all moments of x[senders]
    over real edges, fwd + dx vs the materialized composed twin."""
    b = _batch(seed=7)
    rng = np.random.RandomState(8)
    n = b.x.shape[0]
    f = 40
    x = jnp.asarray(rng.rand(n, f), jnp.float32)
    s, r = jnp.asarray(b.senders), jnp.asarray(b.receivers)
    mask = jnp.asarray(b.edge_mask)
    perm = jnp.asarray(np.argsort(np.asarray(b.senders), kind="stable"),
                       jnp.int32)

    outs = gather_poly_segment(x, s, r, perm, ALL_MOMENTS, mask=mask)
    ref = _refs(x[s], r, mask, n)
    np.testing.assert_allclose(outs[0], ref["sum"], rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(outs[1], ref["sq"], rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        jnp.where(outs[2] <= -_BIG * 0.5, -_BIG, outs[2]),
        jnp.where(ref["mxmn"] <= -_BIG * 0.5, -_BIG, ref["mxmn"]),
        rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(outs[3], ref["cnt"], rtol=1e-6, atol=1e-6)

    def loss_fused(x_):
        su, q, mxmn, cnt = gather_poly_segment(x_, s, r, perm, ALL_MOMENTS,
                                               mask=mask)
        mx = jnp.where(mxmn[:, :f] <= -_BIG * 0.5, 0.0, mxmn[:, :f])
        mn = jnp.where(mxmn[:, f:] <= -_BIG * 0.5, 0.0, -mxmn[:, f:])
        return (jnp.sum(su ** 2) + 0.5 * jnp.sum(q ** 2)
                + jnp.sum(mx ** 2) + jnp.sum(mn ** 3))

    def loss_ref(x_):
        rr = _refs(x_[s], r, mask, n)
        mm = jnp.where(rr["mxmn"] <= -_BIG * 0.5, 0.0, rr["mxmn"])
        return (jnp.sum(rr["sum"] ** 2) + 0.5 * jnp.sum(rr["sq"] ** 2)
                + jnp.sum(mm[:, :f] ** 2) + jnp.sum((-mm[:, f:]) ** 3))

    g1 = jax.grad(loss_fused)(x)
    g2 = jax.grad(loss_ref)(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-4, atol=1e-4)


def test_gather_sum_cnt_only():
    """The SAGE/MFC moment set (sum + cnt): forward and the one-pass
    fused backward (no [E, F] intermediate) vs the composed twin."""
    b = _batch(seed=9)
    rng = np.random.RandomState(10)
    n = b.x.shape[0]
    x = jnp.asarray(rng.rand(n, 32), jnp.float32)
    s, r = jnp.asarray(b.senders), jnp.asarray(b.receivers)
    mask = jnp.asarray(b.edge_mask)
    perm = jnp.asarray(np.argsort(np.asarray(b.senders), kind="stable"),
                       jnp.int32)

    su, cnt = gather_poly_segment(x, s, r, perm, ("sum", "cnt"), mask=mask)
    np.testing.assert_allclose(
        su, jax.ops.segment_sum(x[s] * mask[:, None], r, num_segments=n),
        rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        cnt, jax.ops.segment_sum(mask, r, num_segments=n),
        rtol=1e-6, atol=1e-6)
    # the neighbor-MEAN composition SAGE uses (max(cnt,1) divide)
    mean = su / jnp.maximum(cnt, 1.0)[:, None]
    np.testing.assert_allclose(
        mean, np.asarray(segment.gather_segment_mean(x, b)),
        rtol=1e-5, atol=1e-5)

    g1 = jax.grad(lambda x_: jnp.sum(gather_poly_segment(
        x_, s, r, perm, ("sum", "cnt"), mask=mask)[0] ** 2))(x)
    g2 = jax.grad(lambda x_: jnp.sum(jax.ops.segment_sum(
        x_[s] * mask[:, None], r, num_segments=n) ** 2))(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-4, atol=1e-4)


def test_all_masked_segment_yields_zero_moments():
    """A node with NO real in-edges (every slot masked) must read 0 for
    every cleaned moment — the segment_mean/max/min empty conventions."""
    b = _batch(seed=11)
    e = b.senders.shape[0]
    data = _edge_data(b, seed=12) + 5.0   # strictly positive: a leaked
    ids = jnp.asarray(b.receivers)        # masked max would be visibly > 0
    n = b.x.shape[0]
    mask = jnp.zeros((e,), jnp.float32)   # EVERYTHING masked
    s, q, mxmn, cnt = segment_poly_dense(data, ids, n, ALL_MOMENTS,
                                         valid=mask)
    assert np.all(np.asarray(s) == 0.0)
    assert np.all(np.asarray(q) == 0.0)
    assert np.all(np.asarray(cnt) == 0.0)
    f = data.shape[1]
    mx = jnp.where(mxmn[:, :f] <= -_BIG * 0.5, 0.0, mxmn[:, :f])
    mn = jnp.where(mxmn[:, f:] <= -_BIG * 0.5, 0.0, -mxmn[:, f:])
    assert np.all(np.asarray(mx) == 0.0)
    assert np.all(np.asarray(mn) == 0.0)


def test_dispatcher_fused_matches_fallback(monkeypatch):
    """poly_scatter_segment / poly_gather_segment: the fused dict (marker
    present) must equal the composed dict (marker stripped), including
    the mx/mn empty-segment zero-clean and cnt == degree."""
    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", "fused")
    b = _batch(seed=13)
    assert "edge_perm_sender" in b.extras
    ex = dict(b.extras)
    del ex["edge_perm_sender"]
    b_plain = b.replace(extras=ex)

    data = _edge_data(b, seed=14)
    moments = ("sum", "sq", "mx", "mn", "cnt")
    rf = segment.poly_scatter_segment(data, b, moments)
    rp = segment.poly_scatter_segment(data, b_plain, moments)
    for k in moments:
        np.testing.assert_allclose(np.asarray(rf[k]), np.asarray(rp[k]),
                                   rtol=1e-5, atol=1e-5, err_msg=k)

    rng = np.random.RandomState(15)
    x = jnp.asarray(rng.rand(b.x.shape[0], 24), jnp.float32)
    gf = segment.poly_gather_segment(x, b, moments)
    gp = segment.poly_gather_segment(x, b_plain, moments)
    for k in moments:
        np.testing.assert_allclose(np.asarray(gf[k]), np.asarray(gp[k]),
                                   rtol=1e-5, atol=1e-5, err_msg=k)


def test_dispatch_tally_counts_fused_and_fallback(monkeypatch):
    """The trace-time dispatch tally: a marker-carrying batch counts
    :fused, a marker-less one :scatter, and the width gate falls back
    (the silent-fast-path-loss signal the telemetry manifest surfaces)."""
    from hydragnn_tpu.ops.poly_mp import POLY_MAX_F_MXMN
    from hydragnn_tpu.telemetry import pipeline

    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", "fused")
    b = _batch(seed=16)
    data = _edge_data(b, seed=17, f=16)

    base = pipeline.dispatch_snapshot()
    segment.poly_scatter_segment(data, b, ("sum", "mx"))
    d1 = pipeline.dispatch_snapshot()
    assert d1.get("poly_scatter:fused", 0) \
        == base.get("poly_scatter:fused", 0) + 1

    ex = dict(b.extras)
    del ex["edge_perm_sender"]
    segment.poly_scatter_segment(data, b.replace(extras=ex), ("sum", "mx"))
    d2 = pipeline.dispatch_snapshot()
    assert d2.get("poly_scatter:scatter", 0) \
        == d1.get("poly_scatter:scatter", 0) + 1

    # width gate: F above the mxmn cap must take the composed path even
    # with the marker present — and still be numerically right
    wide = jnp.asarray(
        np.random.RandomState(18).rand(b.senders.shape[0],
                                       POLY_MAX_F_MXMN + 1), jnp.float32)
    out = segment.poly_scatter_segment(wide, b, ("sum", "mx"))
    d3 = pipeline.dispatch_snapshot()
    assert d3.get("poly_scatter:scatter", 0) \
        == d2.get("poly_scatter:scatter", 0) + 1
    np.testing.assert_allclose(
        np.asarray(out["sum"]),
        np.asarray(jax.ops.segment_sum(
            wide * jnp.asarray(b.edge_mask)[:, None],
            jnp.asarray(b.receivers), num_segments=b.x.shape[0])),
        rtol=1e-5, atol=1e-5)

    assert pipeline.dispatch_summary(
        {"poly_scatter:fused": 2}) == "fused"
    assert pipeline.dispatch_summary(
        {"a:fused": 1, "b:scatter": 2}) == "mixed(fused=1,scatter=2)"
