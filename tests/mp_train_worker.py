"""Worker for the 2-process distributed CI leg (the reference's
``mpirun -n 2 pytest --with-mpi`` analog, SURVEY.md §4): initializes
jax.distributed over CPU, runs a small end-to-end training through
run_training (rank-sharded loaders, cross-host metric reduction,
variable-size eval gather) and prints the final losses for the parent
test to compare across ranks."""

import json
import os
import sys

rank = int(sys.argv[1])
world = int(sys.argv[2])
port = sys.argv[3]
scratch = sys.argv[4]

os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}",
    num_processes=world,
    process_id=rank,
)
assert jax.process_count() == world

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))
sys.path.insert(0, os.path.dirname(os.path.abspath(__file__)))
os.chdir(scratch)
os.environ["SERIALIZED_DATA_PATH"] = scratch

import numpy as np

import hydragnn_tpu

with open(os.path.join(os.path.dirname(os.path.abspath(__file__)),
                       "inputs", "ci.json")) as f:
    config = json.load(f)
config["NeuralNetwork"]["Architecture"]["model_type"] = "SAGE"
config["NeuralNetwork"]["Training"]["num_epoch"] = 6
config["Verbosity"]["level"] = 0

if rank == 0:
    for name, path in config["Dataset"]["path"].items():
        n = 120 if name == "train" else 30
        from ci_data import generate_cached

        generate_cached(name, path, n)
from hydragnn_tpu.parallel.comm import host_allreduce

host_allreduce(np.zeros(1))  # barrier after data gen

state, history, fconfig = hydragnn_tpu.run_training(config)
err, tasks, tv, pv = hydragnn_tpu.run_prediction(config)

# digest of the trained params: the global-mesh DP step psums gradients
# across processes every step, so ranks must hold bitwise-identical models
# (the reference's DDP invariant)
import hashlib

h = hashlib.sha256()
for leaf in jax.tree.leaves(jax.device_get(state.params)):
    h.update(np.ascontiguousarray(np.asarray(leaf)).tobytes())
digest = h.hexdigest()[:16]

print(f"MPRESULT rank={rank} val={history['val'][-1]:.8f} "
      f"err={err:.8f} ngather={len(tv[0])} params={digest}")
