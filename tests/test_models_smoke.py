"""Forward-pass smoke matrix: all 9 model types x head configs.

Mirrors the breadth of reference tests/test_graphs.py (which trains all
9 x {single, multihead}); full accuracy training runs live in
test_graphs.py here.
"""

import numpy as np
import pytest
import jax

from hydragnn_tpu.graph import (
    GraphSample,
    HeadSpec,
    PadSpec,
    collate,
    radius_graph,
)
from hydragnn_tpu.models.base import (
    GraphHeadCfg,
    ModelConfig,
    NodeHeadCfg,
    multihead_loss,
)
from hydragnn_tpu.models.create import create_model, init_model
from hydragnn_tpu.models.dimenet import add_dimenet_extras

ALL_MODELS = ["SAGE", "GIN", "GAT", "MFC", "PNA", "CGCNN", "SchNet", "DimeNet", "EGNN"]


def make_samples(n_graphs=3, n_nodes=8, seed=0):
    rng = np.random.RandomState(seed)
    samples = []
    for _ in range(n_graphs):
        pos = rng.rand(n_nodes, 3) * 2.0
        x = rng.rand(n_nodes, 1)
        ei = radius_graph(pos, radius=1.5, max_neighbours=10)
        node_y = np.concatenate([x, x**2, x**3], axis=1)
        graph_y = np.array([node_y.sum()])
        samples.append(
            GraphSample(x=x, pos=pos, edge_index=ei, graph_y=graph_y, node_y=node_y)
        )
    return samples


def make_cfg(model_type, multihead=False, edge_dim=None, equivariance=False,
             node_head_type="mlp"):
    if multihead:
        output_dim = (1, 1, 1, 1)
        output_type = ("graph", "node", "node", "node")
        weights = (20.0, 1.0, 1.0, 1.0)
    else:
        output_dim = (1,)
        output_type = ("graph",)
        weights = (1.0,)
    return ModelConfig(
        model_type=model_type,
        input_dim=1,
        hidden_dim=1 if model_type == "CGCNN" else 8,
        output_dim=output_dim,
        output_type=output_type,
        graph_head=GraphHeadCfg(2, 4, 2, (10, 10)),
        node_head=NodeHeadCfg(2, (4, 4), node_head_type),
        task_weights=weights,
        num_conv_layers=2,
        num_nodes=8,
        edge_dim=edge_dim,
        equivariance=equivariance,
        pna_avg_deg_log=1.5,
        pna_avg_deg_lin=4.0,
        max_degree=10,
        max_neighbours=10,
        num_gaussians=10,
        num_filters=16,
        radius=1.5,
        envelope_exponent=5,
        num_before_skip=1,
        num_after_skip=2,
        num_radial=6,
        num_spherical=7,
        basis_emb_size=8,
        int_emb_size=16,
        out_emb_size=16,
    )


def build_batch(samples, head_specs, with_edge_lengths=False, dimenet=False):
    if with_edge_lengths:
        for s in samples:
            d = s.pos[s.edge_index[0]] - s.pos[s.edge_index[1]]
            s.edge_attr = np.linalg.norm(d, axis=1, keepdims=True)
    pad = PadSpec.for_batch(len(samples), 8, 60)
    batch = collate(samples, pad, head_specs)
    if dimenet:
        batch = add_dimenet_extras(batch, max_triplets=2048)
    return batch


@pytest.mark.parametrize("model_type", ALL_MODELS)
@pytest.mark.parametrize("multihead", [False, True])
def test_forward(model_type, multihead):
    cfg = make_cfg(model_type, multihead)
    specs = [
        HeadSpec(n, t, d)
        for n, t, d in zip(
            ["g", "n1", "n2", "n3"], cfg.output_type, cfg.output_dim
        )
    ]
    samples = make_samples()
    batch = build_batch(samples, specs, dimenet=model_type == "DimeNet")
    model = create_model(cfg)
    variables = init_model(model, batch)
    out = model.apply(
        variables,
        batch,
        train=False,
        mutable=False,
    )
    assert len(out) == len(cfg.output_dim)
    for o, t in zip(out, cfg.output_type):
        expect = batch.num_graphs if t == "graph" else batch.num_nodes
        assert o.shape == (expect, 1)
        assert np.all(np.isfinite(np.asarray(o)))
    total, per_head = multihead_loss(cfg, out, batch)
    assert np.isfinite(float(total))
    assert len(per_head) == len(out)


@pytest.mark.parametrize("model_type", ["PNA", "CGCNN", "SchNet", "EGNN"])
def test_forward_edge_lengths(model_type):
    cfg = make_cfg(model_type, edge_dim=1)
    specs = [HeadSpec("g", "graph", 1)]
    batch = build_batch(make_samples(), specs, with_edge_lengths=True)
    model = create_model(cfg)
    variables = init_model(model, batch)
    out = model.apply(variables, batch, train=False, mutable=False)
    assert np.all(np.isfinite(np.asarray(out[0])))


@pytest.mark.parametrize("model_type", ["EGNN", "SchNet"])
def test_forward_equivariant(model_type):
    cfg = make_cfg(model_type, equivariance=True)
    specs = [HeadSpec("g", "graph", 1)]
    batch = build_batch(make_samples(), specs)
    model = create_model(cfg)
    variables = init_model(model, batch)
    out = model.apply(variables, batch, train=False, mutable=False)
    assert np.all(np.isfinite(np.asarray(out[0])))


@pytest.mark.parametrize(
    "model_type", ["SAGE", "GIN", "GAT", "MFC", "PNA", "SchNet", "DimeNet", "EGNN"]
)
def test_forward_conv_node_head(model_type):
    cfg = make_cfg(model_type, multihead=True, node_head_type="conv")
    specs = [
        HeadSpec(n, t, d)
        for n, t, d in zip(["g", "n1", "n2", "n3"], cfg.output_type, cfg.output_dim)
    ]
    batch = build_batch(make_samples(), specs, dimenet=model_type == "DimeNet")
    model = create_model(cfg)
    variables = init_model(model, batch)
    rngs = {"dropout": jax.random.PRNGKey(0)}
    out, _ = model.apply(
        variables, batch, train=True, rngs=rngs, mutable=["batch_stats"]
    )
    for o in out:
        assert np.all(np.isfinite(np.asarray(o)))
