"""Quantized inference (hydragnn_tpu/quant + serve/engine policy gate,
docs/SERVING.md "Quantized inference"): int8 per-channel round-trip
exactness, bf16/int8 engine parity against f32 within tolerance,
resident-bytes ratios, tolerance-reject fallback (bit-identical f32),
zero steady-state recompiles per policy, and hot reload + rollback with
a quantized active policy."""

import os
import pickle

import numpy as np
import pytest

from hydragnn_tpu.graph.batch import GraphSample, HeadSpec, PadSpec, collate
from hydragnn_tpu.graph.neighborlist import radius_graph
from hydragnn_tpu.models.base import GraphHeadCfg, ModelConfig
from hydragnn_tpu.models.create import create_model
from hydragnn_tpu.quant import (
    QTensor,
    apply_policy,
    check_policy,
    dequantize,
    quantize_int8,
    tree_nbytes,
)
from hydragnn_tpu.serve import (
    InferenceEngine,
    InferenceState,
    ServingConfig,
)

_HEADS = [HeadSpec("energy", "graph", 1)]
_PADS = [PadSpec.for_batch(2, 16, 64)]


def _sample(n=6, seed=0):
    rng = np.random.RandomState(seed)
    pos = rng.rand(n, 3).astype(np.float32) * 2.0
    return GraphSample(x=rng.rand(n, 1).astype(np.float32), pos=pos,
                       edge_index=radius_graph(pos, 1.2, 8))


@pytest.fixture(scope="module")
def setup():
    import jax

    cfg = ModelConfig(
        model_type="SAGE", input_dim=1, hidden_dim=32, output_dim=(1,),
        output_type=("graph",), graph_head=GraphHeadCfg(1, 32, 1, (32,)),
        node_head=None, task_weights=(1.0,), num_conv_layers=2)
    model = create_model(cfg)
    example = collate([_sample()], _PADS[0], _HEADS)
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        example, train=False)
    state = InferenceState(step=0, params=variables["params"],
                           batch_stats=variables.get("batch_stats", {}))
    return cfg, state


def _engine(cfg, state, policy, tol=0.05):
    eng = InferenceEngine(
        cfg, state, _HEADS, _PADS,
        serving=ServingConfig(quant_policy=policy, quant_tolerance=tol,
                              max_nodes_per_graph=16,
                              max_edges_per_graph=64))
    eng.warmup()
    return eng


@pytest.fixture(scope="module")
def engines(setup):
    """One warmed engine per policy (ONE bucket each, for budget)."""
    cfg, state = setup
    return {p: _engine(cfg, state, p) for p in ("f32", "bf16", "int8")}


# ---------------------------------------------------------------------------
# quant primitives (no engine)
# ---------------------------------------------------------------------------


def test_int8_roundtrip_exact_on_synthetic_weights():
    """Weights built as int8 grids times power-of-two per-channel scales
    survive quantize -> dequantize EXACTLY (scale recovery is exact and
    q * 2^-k fits bf16's mantissa)."""
    rng = np.random.RandomState(0)
    q0 = rng.randint(-127, 128, size=(24, 8)).astype(np.float32)
    q0[0, :] = 127.0  # pin each channel's absmax so scale = 2^-k exactly
    scales = 2.0 ** -rng.randint(1, 6, size=8).astype(np.float32)
    w = q0 * scales[None, :]
    qt = quantize_int8(w)
    assert np.asarray(qt.q).dtype == np.int8
    np.testing.assert_array_equal(np.asarray(qt.scale), scales)
    deq32 = np.asarray(dequantize(qt, dtype=np.float32))
    np.testing.assert_array_equal(deq32, w)
    # and the bf16 operand the matmuls actually consume is exact too
    deq16 = np.asarray(dequantize(qt)).astype(np.float32)
    np.testing.assert_array_equal(deq16, w)


def test_int8_per_channel_scales_are_independent():
    w = np.zeros((16, 3), np.float32)
    w[:, 0] = np.linspace(-1.27, 1.27, 16)
    w[:, 1] = np.linspace(-254.0, 254.0, 16)
    w[:, 2] = 0.0  # all-zero channel: scale 1, dequant exactly zero
    qt = quantize_int8(w)
    s = np.asarray(qt.scale)
    assert s.shape == (3,)
    assert s[1] == pytest.approx(s[0] * 200.0, rel=1e-6)
    assert s[2] == 1.0
    deq = np.asarray(dequantize(qt, dtype=np.float32))
    np.testing.assert_array_equal(deq[:, 2], 0.0)
    # per-channel quantization error bounded by scale/2 per element
    assert np.max(np.abs(deq - w)) <= 0.5 * s.max()


def test_apply_policy_bytes_ratios():
    """bf16 == 0.5x f32; int8 on kernel-dominated trees <= 0.3x (the
    HBM-per-replica acceptance number)."""
    rng = np.random.RandomState(1)
    params = {f"layer{i}": {"kernel": rng.randn(64, 64).astype(np.float32),
                            "bias": rng.randn(64).astype(np.float32)}
              for i in range(4)}
    state = InferenceState(step=0, params=params, batch_stats={})
    f32b = tree_nbytes(state.params)
    bf16b = tree_nbytes(apply_policy(state, "bf16").params)
    int8b = tree_nbytes(apply_policy(state, "int8").params)
    assert bf16b == f32b // 2
    assert int8b <= 0.3 * f32b
    # kernels became QTensors, biases fell to bf16
    import jax

    qparams = apply_policy(state, "int8").params
    assert isinstance(qparams["layer0"]["kernel"], QTensor)
    assert str(qparams["layer0"]["bias"].dtype) == "bfloat16"
    # 1-row matrices are NOT quantized (scale overhead >= saving)
    tiny = InferenceState(
        step=0, params={"k": np.ones((1, 64), np.float32)}, batch_stats={})
    assert not isinstance(apply_policy(tiny, "int8").params["k"], QTensor)
    with pytest.raises(ValueError):
        check_policy("fp8")


# ---------------------------------------------------------------------------
# engine policy gate
# ---------------------------------------------------------------------------


def test_bf16_and_int8_parity_within_tolerance(engines):
    samples = [_sample(5, seed=11), _sample(7, seed=12)]
    ref = engines["f32"].predict_arrays(samples)
    for policy in ("bf16", "int8"):
        eng = engines[policy]
        q = eng.quant_stats()
        assert q["active"] == policy and not q["fallback"]
        assert q["golden_max_delta"] is not None
        assert q["golden_max_delta"] <= q["tolerance"]
        out = eng.predict_arrays(samples)
        for a, b in zip(out, ref):
            np.testing.assert_allclose(a, b, atol=0.05)
    # resident bytes: bf16 half, int8 under half of that plus scales
    f32b = engines["f32"].quant_stats()["param_bytes"]
    assert engines["bf16"].quant_stats()["param_bytes"] == f32b // 2
    assert engines["int8"].quant_stats()["param_bytes"] < 0.35 * f32b


def test_zero_steady_state_recompiles_per_policy(engines):
    """The cache-counter contract EXTENDED per policy, not relaxed:
    warmup = every bucket for the active policy + the one f32 golden
    reference probe; steady state hits for every policy."""
    for policy, eng in engines.items():
        eng.predict_samples([_sample(5, seed=21)])
        eng.predict_samples([_sample(6, seed=22)])
        st = eng.cache_stats()
        assert st["misses"] == 0, policy
        assert st["hit_rate"] == 1.0, policy
        expected_warmups = len(_PADS) + (0 if policy == "f32" else 1)
        assert st["warmup_compiles"] == expected_warmups, policy


def test_tolerance_reject_falls_back_to_f32(setup, engines):
    """An unmeetable tolerance rejects the policy: f32 keeps serving
    (bit-identical to the f32 engine), the fallback is visible in
    quant_stats, and a quant_reject health event is tallied."""
    cfg, state = setup
    eng = _engine(cfg, state, "int8", tol=1e-12)
    q = eng.quant_stats()
    assert q["requested"] == "int8" and q["active"] == "f32"
    assert q["fallback"] is True
    assert eng.telemetry.health_counts.get("quant_reject") == 1
    s = [_sample(5, seed=31)]
    np.testing.assert_array_equal(
        eng.predict_arrays(s)[0], engines["f32"].predict_arrays(s)[0])
    assert eng.cache_stats()["misses"] == 0


def test_hot_reload_and_rollback_with_quantized_policy(setup, tmp_path):
    """A fresh f32 checkpoint hot-swaps into an int8-active engine with
    zero recompiles (the candidate is quantized BEFORE validation, so
    avals match); rollback restores the previous quantized state
    bit-exactly; a NaN-corrupted candidate is rejected through the
    quantize path."""
    import jax

    cfg, state = setup
    eng = _engine(cfg, state, "int8")
    s = [_sample(6, seed=41)]
    before = eng.predict_arrays(s)[0]

    model = create_model(cfg)
    example = collate([_sample()], _PADS[0], _HEADS)
    v2 = model.init(
        {"params": jax.random.PRNGKey(9), "dropout": jax.random.PRNGKey(1)},
        example, train=False)
    ckpt = os.path.join(str(tmp_path), "cand.pk")
    with open(ckpt, "wb") as f:  # graftlint: disable=ROB002 (test fixture in tmp dir; crash durability irrelevant)
        pickle.dump({"step": 5, "params": jax.device_get(v2["params"]),
                     "batch_stats": jax.device_get(
                         v2.get("batch_stats", {}))}, f)
    rep = eng.reload_from_checkpoint(ckpt)
    assert rep["step"] == 5
    after = eng.predict_arrays(s)[0]
    assert not np.array_equal(after, before)
    assert eng.cache_stats()["misses"] == 0
    assert eng.quant_stats()["active"] == "int8"
    assert eng.rollback()
    np.testing.assert_array_equal(eng.predict_arrays(s)[0], before)
    assert eng.cache_stats()["misses"] == 0

    from hydragnn_tpu.serve.engine import ReloadValidationError

    bad = jax.tree_util.tree_map(
        lambda a: np.full_like(np.asarray(a), np.nan)
        if np.issubdtype(np.asarray(a).dtype, np.floating) else a,
        jax.device_get(v2["params"]))
    with pytest.raises(ReloadValidationError):
        eng.reload_state(InferenceState(
            step=9, params=bad,
            batch_stats=jax.device_get(v2.get("batch_stats", {}))))
    np.testing.assert_array_equal(eng.predict_arrays(s)[0], before)


# ---------------------------------------------------------------------------
# config plumbing
# ---------------------------------------------------------------------------


def test_serving_config_quant_knobs(monkeypatch):
    with pytest.raises(ValueError):
        ServingConfig(quant_policy="fp8")
    with pytest.raises(ValueError):
        ServingConfig(quant_tolerance=-1.0)
    cfg = ServingConfig.from_section(
        {"quant_policy": "bf16", "quant_tolerance": 0.01})
    assert cfg.quant_policy == "bf16" and cfg.quant_tolerance == 0.01
    monkeypatch.setenv("HYDRAGNN_SERVE_QUANT_POLICY", "int8")
    monkeypatch.setenv("HYDRAGNN_SERVE_QUANT_TOL", "0.2")
    cfg = ServingConfig.from_section({"quant_policy": "bf16"})
    assert cfg.quant_policy == "int8"      # env wins over config
    assert cfg.quant_tolerance == 0.2
    from hydragnn_tpu.serve.config import serving_defaults

    d = serving_defaults()
    assert d["quant_policy"] == "f32"
    assert d["quant_tolerance"] == 0.05
