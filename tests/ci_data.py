"""Shared cached generation of the deterministic CI dataset.

One place for the cache-invalidation logic: the seed comes from zlib.crc32
(str hash() is randomized per process, so a hash-derived seed would make the
cached dataset differ run-to-run — and some draws miss the accuracy
thresholds), and a seed-stamp marker file makes caches generated under a
different seed scheme or sample count self-invalidating.
"""

import os
import zlib

from hydragnn_tpu.data.synthetic import deterministic_graph_data


def generate_cached(name: str, path: str, n: int) -> None:
    """Generate ``n`` LSMS files under ``path`` if the cache is missing or
    was created with a different (seed, n)."""
    import glob

    os.makedirs(path, exist_ok=True)
    seed = zlib.crc32(name.encode()) % 1000
    # stamp lives BESIDE the dir: raw loaders treat every file inside as data
    base = os.path.normpath(path)
    stamp = base + f".seed{seed}_n{n}.stamp"
    if os.path.exists(stamp) and os.listdir(path):
        return
    # drop ALL stale stamps for this path first, or a later regeneration with
    # a different n would leave the old stamp matching a wrong-size cache
    for old in glob.glob(base + ".seed*.stamp"):
        os.remove(old)
    for f in os.listdir(path):
        os.remove(os.path.join(path, f))
    deterministic_graph_data(path, number_configurations=n, seed=seed)
    open(stamp, "w").close()
