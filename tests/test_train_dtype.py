"""bf16/f32-accum training dtype policy (docs/PERF.md PR-15):
``Training.train_dtype_policy`` / HYDRAGNN_TRAIN_DTYPE run the train-step
forward/backward in bf16 with f32 master params, optimizer state and
accumulators — gated by a step-0 golden-replay probe that falls back
LOUDLY to f32, with the verdict persisted in the resume bundle so a
preempted run replays the same program (crash/resume bit-parity)."""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hydragnn_tpu.graph.batch import HeadSpec, PadSpec, collate
from hydragnn_tpu.models.base import GraphHeadCfg, ModelConfig
from hydragnn_tpu.models.create import create_model
from hydragnn_tpu.parallel.mesh import stack_batches
from hydragnn_tpu.resilience import load_resume_bundle, resume_dir
from hydragnn_tpu.telemetry import MetricsLogger
from hydragnn_tpu.train.optimizer import select_optimizer
from hydragnn_tpu.train.trainer import (
    create_train_state,
    make_scan_train_step,
    make_train_step,
)

from test_resilience import (  # reuse the deterministic-loader harness
    _Loaders,
    _batch,
    _fresh_skeleton,
    _leaves_equal,
    _model,
    _run,
)


@pytest.fixture(autouse=True)
def _clean_env(monkeypatch):
    monkeypatch.delenv("HYDRAGNN_TRAIN_DTYPE", raising=False)


# ---------------------------------------------------------------------------
# default OFF => byte-identical HLO on all three step paths
# ---------------------------------------------------------------------------


def test_policy_off_unchanged_hlo_local_and_scan():
    cfg, model = _model()
    opt = select_optimizer({"type": "AdamW", "learning_rate": 1e-3})
    b = _batch()
    s0 = create_train_state(model, b, opt)

    base = jax.jit(make_train_step(model, cfg, opt)).lower(s0, b).as_text()
    off = jax.jit(make_train_step(model, cfg, opt, dtype_policy="f32")
                  ).lower(s0, b).as_text()
    on = jax.jit(make_train_step(model, cfg, opt, dtype_policy="bf16")
                 ).lower(s0, b).as_text()
    assert off == base  # explicit "f32" is the default — same program
    assert on != base and "bf16" in on
    assert "bf16" not in base

    sb = stack_batches([_batch(seed=1), _batch(seed=2)])
    sbase = jax.jit(make_scan_train_step(model, cfg, opt, None, 2)
                    ).lower(s0, sb).as_text()
    soff = jax.jit(make_scan_train_step(model, cfg, opt, None, 2,
                                        dtype_policy="f32")
                   ).lower(s0, sb).as_text()
    son = jax.jit(make_scan_train_step(model, cfg, opt, None, 2,
                                       dtype_policy="bf16")
                  ).lower(s0, sb).as_text()
    assert soff == sbase
    assert son != sbase and "bf16" in son


def test_policy_off_unchanged_hlo_mesh_dp():
    from hydragnn_tpu.parallel.mesh import (
        make_dp_train_step,
        make_mesh,
        replicate_state,
    )

    cfg, model = _model()
    opt = select_optimizer({"type": "AdamW", "learning_rate": 1e-3})
    mesh = make_mesh()
    n_dev = len(jax.devices())
    batches = stack_batches([_batch(seed=i) for i in range(n_dev)])
    s0 = replicate_state(
        create_train_state(model, _batch(), opt), mesh)

    base = make_dp_train_step(model, cfg, opt, mesh).lower(
        s0, batches).as_text()
    off = make_dp_train_step(model, cfg, opt, mesh, dtype_policy="f32"
                             ).lower(s0, batches).as_text()
    on = make_dp_train_step(model, cfg, opt, mesh, dtype_policy="bf16"
                            ).lower(s0, batches).as_text()
    assert off == base
    assert on != base and "bf16" in on


def test_bf16_policy_keeps_master_state_f32():
    """The policy changes COMPUTE dtype only: updated params, optimizer
    state and batch stats come back f32 (master copies), and the loss
    tracks the f32 step within bf16 tolerance."""
    cfg, model = _model()
    opt = select_optimizer({"type": "AdamW", "learning_rate": 1e-3})
    b = _batch()
    s0 = create_train_state(model, b, opt)

    sf, mf = jax.jit(make_train_step(model, cfg, opt))(s0, b)
    sb, mb = jax.jit(make_train_step(model, cfg, opt, dtype_policy="bf16")
                     )(s0, b)
    for leaf in jax.tree.leaves((sb.params, sb.opt_state, sb.batch_stats)):
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.float32
    ref = float(mf["loss"])
    assert abs(float(mb["loss"]) - ref) < 0.05 * (abs(ref) + 1e-3)


# ---------------------------------------------------------------------------
# trainer-level gate: accept, reject-with-bit-identical-fallback, env knob
# ---------------------------------------------------------------------------


def test_gate_accepts_bf16_policy_via_env(tmp_path, monkeypatch):
    """One run covers both accept paths: the env knob overlays the
    config default, and the golden gate passes on the toy model (the
    config-route accept is exercised by the resume-parity test below)."""
    monkeypatch.setenv("HYDRAGNN_TRAIN_DTYPE", "bf16")
    loaders = _Loaders(n_train=16)
    _, hist = _run(loaders, tmp_path, "bf16_on", num_epoch=1)
    assert hist["pipeline"]["train_dtype"] == "bf16"
    assert hist["pipeline"]["train_dtype_requested"] == "bf16"
    assert np.isfinite(hist["train"][0])


def test_gate_reject_falls_back_bit_identical(tmp_path, monkeypatch):
    """A rejected bf16 request must train EXACTLY as an unrequested run:
    same f32 program, bit-identical params — plus a loud
    `train_dtype_reject` health event."""
    import hydragnn_tpu.train.trainer as trainer_mod

    loaders = _Loaders(n_train=16)
    state_ref, hist_ref = _run(loaders, tmp_path, "f32_ref", num_epoch=1)
    assert hist_ref["pipeline"]["train_dtype"] == "f32"

    # an impossible bound rejects every model (drift >= 0 > -1 fails)
    monkeypatch.setattr(trainer_mod, "_TRAIN_DTYPE_TOL", -1.0)
    tele = MetricsLogger.disabled()
    with pytest.warns(UserWarning, match="REJECTED"):
        state_rej, hist_rej = _run(
            loaders, tmp_path, "bf16_rejected", num_epoch=1,
            training_extra={"train_dtype_policy": "bf16"},
            telemetry=tele)
    assert hist_rej["pipeline"]["train_dtype"] == "f32"
    assert hist_rej["pipeline"]["train_dtype_requested"] == "bf16"
    assert tele.health_counts.get("train_dtype_reject") == 1
    assert _leaves_equal(state_rej.params, state_ref.params)
    assert _leaves_equal(state_rej.opt_state, state_ref.opt_state)


def test_config_validates_train_dtype_policy():
    from hydragnn_tpu.quant import check_train_policy

    assert check_train_policy("f32") == "f32"
    assert check_train_policy("bf16") == "bf16"
    with pytest.raises(ValueError, match="train dtype policy"):
        check_train_policy("int8")  # inference-only policy
    with pytest.raises(ValueError, match="train dtype policy"):
        check_train_policy("bfloat16")  # the knob vocabulary is bf16

    here = os.path.join(os.path.dirname(__file__), "inputs", "ci.json")
    from hydragnn_tpu.config.config import DatasetStats, finalize

    config = json.load(open(here))
    config["NeuralNetwork"]["Architecture"]["model_type"] = "SAGE"
    stats = DatasetStats(num_nodes_sample=8, graph_size_variable=True)
    out = finalize(config, stats)
    # default written back (same contract as zero_stage)
    assert out["NeuralNetwork"]["Training"]["train_dtype_policy"] == "f32"
    config["NeuralNetwork"]["Training"]["train_dtype_policy"] = "fp16"
    with pytest.raises(ValueError, match="train dtype policy"):
        finalize(config, stats)


# ---------------------------------------------------------------------------
# int8_edge pilot: fake-quantized edge-MLP kernels behind the same gate
# ---------------------------------------------------------------------------


def test_int8_edge_fake_quant_scope_and_ste():
    """fake_quant_edge_params touches exactly the edge-MLP kernels:
    int8 round-trip on matching 2-D kernels, identity on biases, on
    non-edge modules and on sub-quantizable leaves — with a
    straight-through gradient everywhere."""
    from hydragnn_tpu.quant import fake_quant_edge_params

    rng = np.random.RandomState(0)
    params = {"params": {
        "filter_0": {"kernel": jnp.asarray(rng.randn(8, 16), jnp.float32),
                     "bias": jnp.zeros((16,), jnp.float32)},
        "lin_f": {"kernel": jnp.asarray(rng.randn(8, 4), jnp.float32)},
        "lin1": {"kernel": jnp.asarray(rng.randn(8, 4), jnp.float32)},
        # single-row kernel: below the quantizable floor, must pass through
        "edge_mlp_0": {"kernel": jnp.asarray(rng.randn(1, 4), jnp.float32)},
    }}
    fq = fake_quant_edge_params(params)
    p, q = params["params"], fq["params"]
    assert not np.array_equal(p["filter_0"]["kernel"], q["filter_0"]["kernel"])
    assert np.allclose(p["filter_0"]["kernel"], q["filter_0"]["kernel"],
                       atol=0.05)  # int8 round-trip stays near the master
    assert not np.array_equal(p["lin_f"]["kernel"], q["lin_f"]["kernel"])
    assert np.array_equal(p["filter_0"]["bias"], q["filter_0"]["bias"])
    assert np.array_equal(p["lin1"]["kernel"], q["lin1"]["kernel"])
    assert np.array_equal(p["edge_mlp_0"]["kernel"], q["edge_mlp_0"]["kernel"])

    # straight-through estimator: d(sum fq(x))/dx == 1 for every leaf
    grads = jax.grad(lambda t: sum(
        jnp.sum(l) for l in jax.tree.leaves(fake_quant_edge_params(t))
    ))(params)
    for leaf in jax.tree.leaves(grads):
        assert np.array_equal(leaf, np.ones_like(leaf))


def test_int8_edge_step_quantizes_schnet_filters():
    """On a model that HAS edge MLPs (SchNet's filter network) the
    int8_edge step produces real-but-small drift from f32, while the
    master params the optimizer updates stay f32."""
    from test_mixed_precision import _setup

    cfg, batch = _setup()
    model = create_model(cfg)
    opt = select_optimizer({"type": "AdamW", "learning_rate": 1e-3})
    s0 = create_train_state(model, batch, opt)

    sf, mf = jax.jit(make_train_step(model, cfg, opt))(s0, batch)
    si, mi = jax.jit(make_train_step(model, cfg, opt,
                                     dtype_policy="int8_edge"))(s0, batch)
    ref, got = float(mf["loss"]), float(mi["loss"])
    assert np.isfinite(got)
    assert got != ref  # the filter kernels really were rounded
    assert abs(got - ref) < 0.05 * (abs(ref) + 1e-3)
    for leaf in jax.tree.leaves((si.params, si.opt_state, si.batch_stats)):
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.float32


def test_gate_accepts_int8_edge(tmp_path):
    """The golden-replay gate accepts an int8_edge request whose drift is
    inside tolerance and persists the verdict (the toy SAGE model has no
    edge MLPs, so the pilot program replays the f32 numbers exactly)."""
    loaders = _Loaders(n_train=16)
    _, hist = _run(loaders, tmp_path, "int8_on", num_epoch=1,
                   training_extra={"train_dtype_policy": "int8_edge"})
    assert hist["pipeline"]["train_dtype"] == "int8_edge"
    assert hist["pipeline"]["train_dtype_requested"] == "int8_edge"
    assert np.isfinite(hist["train"][0])


def test_gate_int8_edge_reject_falls_back_bit_identical(tmp_path, monkeypatch):
    """A rejected int8_edge request trains EXACTLY as an unrequested f32
    run, with the same loud train_dtype_reject health event bf16 uses."""
    import hydragnn_tpu.train.trainer as trainer_mod

    loaders = _Loaders(n_train=16)
    state_ref, hist_ref = _run(loaders, tmp_path, "f32_ref8", num_epoch=1)
    assert hist_ref["pipeline"]["train_dtype"] == "f32"

    monkeypatch.setattr(trainer_mod, "_TRAIN_DTYPE_TOL", -1.0)
    tele = MetricsLogger.disabled()
    with pytest.warns(UserWarning, match="REJECTED"):
        state_rej, hist_rej = _run(
            loaders, tmp_path, "int8_rejected", num_epoch=1,
            training_extra={"train_dtype_policy": "int8_edge"},
            telemetry=tele)
    assert hist_rej["pipeline"]["train_dtype"] == "f32"
    assert hist_rej["pipeline"]["train_dtype_requested"] == "int8_edge"
    assert tele.health_counts.get("train_dtype_reject") == 1
    assert _leaves_equal(state_rej.params, state_ref.params)
    assert _leaves_equal(state_rej.opt_state, state_ref.opt_state)


# ---------------------------------------------------------------------------
# crash/resume bit-parity under the policy
# ---------------------------------------------------------------------------


def test_crash_and_resume_bit_parity_bf16(tmp_path, monkeypatch):
    """The accept verdict rides the resume bundle: the resumed run reuses
    it (no re-probe) and continues the SAME bf16 program — final params
    bit-identical to the uninterrupted bf16 run."""
    monkeypatch.delenv("HYDRAGNN_CHAOS_PREEMPT_STEP", raising=False)
    loaders = _Loaders(n_train=24, batch_size=8)  # 3 steps/epoch
    extra = {"train_dtype_policy": "bf16"}

    state_a, hist_a = _run(loaders, tmp_path, "bf16_full", num_epoch=2,
                           training_extra=extra)
    assert "preempted" not in hist_a
    assert hist_a["pipeline"]["train_dtype"] == "bf16"

    monkeypatch.setenv("HYDRAGNN_CHAOS_PREEMPT_STEP", "4")  # mid-epoch 2
    _, hist_b = _run(loaders, tmp_path, "bf16_cut", num_epoch=2,
                     training_extra=extra)
    assert hist_b.get("preempted") is True
    monkeypatch.delenv("HYDRAGNN_CHAOS_PREEMPT_STEP")

    rdir = resume_dir(str(tmp_path), "bf16_cut")
    bundle = load_resume_bundle(_fresh_skeleton(loaders), rdir)
    assert bundle is not None
    state_r, meta = bundle
    assert meta["pipeline"]["train_dtype"] == "bf16"
    state_c, hist_c = _run(loaders, tmp_path, "bf16_cut", num_epoch=2,
                           training_extra=extra,
                           resume_meta=meta, state=state_r)
    assert "preempted" not in hist_c
    assert hist_c["pipeline"]["train_dtype"] == "bf16"

    assert _leaves_equal(state_c.params, state_a.params)
    assert _leaves_equal(state_c.opt_state, state_a.opt_state)
