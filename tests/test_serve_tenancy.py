"""Closed-loop autoscaler + multi-tenant fleet (docs/SERVING.md
"Multi-tenant fleet & autoscaler"): the FleetAutoscaler state machine
(hysteresis, cooldown, bounds, cold-start never scales) on a fake
clock, supervisor scale-up/scale-down through the replica factory
(zero-drop retirement, chaos-failed spawns absorbed by the backoff
restart machinery), tenant-aware routing (``model`` field -> per-tenant
fork engines behind the bounded LRU, 404 for unknown tenants with NO
failover), per-tenant admission budgets + chaos hot-tenant shedding
(one tenant's 429s leave the others serving), the engine
AOT-executable LRU, and the new Serving/FleetChaos knob plumbing.

Tier-1 budget discipline: same as test_serve_fleet.py — ONE tiny SAGE
engine with ONE bucket compiled once for the module; replicas AND
tenants are ``engine.fork()``s sharing that compile cache, so
multi-tenant fleets cost milliseconds and tenant admission costs zero
compiles.
"""

import json
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from hydragnn_tpu.graph.batch import GraphSample, HeadSpec, PadSpec, collate
from hydragnn_tpu.graph.neighborlist import radius_graph
from hydragnn_tpu.models.base import GraphHeadCfg, ModelConfig
from hydragnn_tpu.models.create import create_model
from hydragnn_tpu.resilience import FleetChaos
from hydragnn_tpu.serve import (
    DEFAULT_TENANT,
    FleetAutoscaler,
    FleetRouter,
    FleetSupervisor,
    InProcessReplica,
    InferenceEngine,
    InferenceState,
    ServingConfig,
)
from hydragnn_tpu.serve.batcher import RequestShedError

_HEADS = [HeadSpec("energy", "graph", 1)]


def _sample(n=6, seed=0):
    rng = np.random.RandomState(seed)
    pos = rng.rand(n, 3).astype(np.float32) * 2.0
    return GraphSample(x=rng.rand(n, 1).astype(np.float32), pos=pos,
                       edge_index=radius_graph(pos, 1.2, 8))


@pytest.fixture(scope="module")
def engine():
    """One tiny SAGE engine, ONE bucket, compiled once for the module;
    replicas and tenants all fork it (shared executable cache)."""
    import jax

    cfg = ModelConfig(
        model_type="SAGE", input_dim=1, hidden_dim=8, output_dim=(1,),
        output_type=("graph",), graph_head=GraphHeadCfg(1, 8, 1, (8,)),
        node_head=None, task_weights=(1.0,), num_conv_layers=2)
    model = create_model(cfg)
    pads = [PadSpec.for_batch(4, 16, 64)]
    example = collate([_sample()], pads[0], _HEADS)
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        example, train=False)
    state = InferenceState(step=0, params=variables["params"],
                           batch_stats=variables.get("batch_stats", {}))
    eng = InferenceEngine(cfg, state, _HEADS, pads)
    eng.warmup()
    return eng


class _Tel:
    """Recording telemetry stub (same shape as test_serve_fleet's)."""

    def __init__(self):
        self.events = []
        self._lock = threading.Lock()

    def health(self, kind, **fields):
        with self._lock:
            self.events.append((kind, fields))

    @property
    def health_counts(self):
        with self._lock:
            out = {}
            for k, _ in self.events:
                out[k] = out.get(k, 0) + 1
            return out

    def kinds(self, kind):
        with self._lock:
            return [f for k, f in self.events if k == kind]

    def serve_step(self, *a, **kw):
        # the micro-batcher emits a full step record per flush when a
        # replica shares this recording stub (tel_replicas=True)
        pass


def _mk_router(engine, n=2, tenants=("ta", "tb"), fleet_chaos=None,
               tel_replicas=False, **overrides):
    """Multi-tenant fleet helper: every replica (including ones the
    autoscaler adds through the factory) hosts the same tenant set as
    fork closures of the module engine.  ``tel_replicas`` routes the
    replicas' own events (tenant_evict) into the recording telemetry
    instead of the disabled logger."""
    kw = dict(port=0, max_wait_ms=2, request_deadline_ms=10_000.0,
              breaker_threshold=2, breaker_cooldown_s=0.25,
              predict_timeout_s=5.0, fleet_probe_s=0.02,
              fleet_restart_backoff_s=0.05,
              fleet_restart_backoff_max_s=0.4, fleet_max_restarts=6,
              fleet_restart_window_s=30.0, fleet_drain_timeout_s=5.0)
    kw.update(overrides)
    serving = ServingConfig(**kw)
    tel = _Tel()
    from hydragnn_tpu.telemetry import MetricsLogger

    tfs = {name: engine.fork for name in tenants}

    rtel = tel if tel_replicas else MetricsLogger.disabled()

    def factory(i):
        return InProcessReplica(i, engine.fork, serving, rtel,
                                tenant_factories=tfs)

    replicas = [factory(i) for i in range(n)]
    fleet = FleetSupervisor(replicas, serving, telemetry=tel,
                            chaos=fleet_chaos, replica_factory=factory)
    router = FleetRouter(fleet, serving=serving, cfg=engine.cfg,
                         telemetry=tel)
    router.start()
    return router


def _wait_until(cond, timeout=10.0, step=0.01):
    t0 = time.monotonic()
    while time.monotonic() - t0 < timeout:
        if cond():
            return True
        time.sleep(step)
    return False


def _post(port, path, obj, timeout=30.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _get(port, path, timeout=10.0):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
        return json.loads(r.read())


def _sample_json(s, **extra):
    return {"x": s.x.tolist(), "pos": s.pos.tolist(),
            "edge_index": s.edge_index.tolist(), **extra}


# ---------------------------------------------------------------------------
# FleetAutoscaler: the pure state machine on a fake clock
# ---------------------------------------------------------------------------


def _scaler(**overrides):
    kw = dict(fleet_min_replicas=1, fleet_max_replicas=4,
              autoscale_up_frac=0.5, autoscale_up_ticks=3,
              autoscale_quiet_s=60.0, autoscale_cooldown_s=30.0,
              request_deadline_ms=10_000.0)
    kw.update(overrides)
    return FleetAutoscaler(ServingConfig(port=0, **kw))


def test_autoscaler_disabled_without_max():
    a = _scaler(fleet_max_replicas=0)
    assert not a.enabled()
    assert a.evaluate(1e9, 1.0, 1, now=0.0) is None


def test_scale_up_after_exactly_up_ticks():
    """est = queued/rate = 100 s >> 5 s threshold: the decision fires
    on the up_ticks-th CONSECUTIVE hot tick, not before."""
    a = _scaler()
    assert a.evaluate(100.0, 1.0, 1, now=0.0) is None
    assert a.evaluate(100.0, 1.0, 1, now=1.0) is None
    d = a.evaluate(100.0, 1.0, 1, now=2.0)
    assert d is not None and d.direction == "up"
    assert d.signal == pytest.approx(100.0) and d.live == 1


def test_hysteresis_one_cool_tick_resets():
    a = _scaler()
    a.evaluate(100.0, 1.0, 1, now=0.0)
    a.evaluate(100.0, 1.0, 1, now=1.0)
    # est 1 s < 5 s threshold — the streak resets
    assert a.evaluate(1.0, 1.0, 1, now=2.0) is None
    assert a.evaluate(100.0, 1.0, 1, now=3.0) is None
    assert a.evaluate(100.0, 1.0, 1, now=4.0) is None
    assert a.evaluate(100.0, 1.0, 1, now=5.0).direction == "up"


def test_cold_start_never_scales_up():
    """No drain-rate sample -> no backlog estimate -> never hot, same
    rule as the admission shed's cold-start never-sheds."""
    a = _scaler(autoscale_up_ticks=1)
    for t in range(5):
        assert a.evaluate(1e6, 0.0, 1, now=float(t)) is None
    assert a.state()["est_wait_s"] is None


def test_up_bounded_by_max_replicas():
    a = _scaler(autoscale_up_ticks=1, fleet_max_replicas=2)
    assert a.evaluate(100.0, 1.0, 2, now=0.0) is None  # live == max
    assert a.evaluate(100.0, 1.0, 1, now=1.0).direction == "up"


def test_cooldown_blocks_back_to_back_decisions():
    a = _scaler(autoscale_up_ticks=1, autoscale_cooldown_s=10.0)
    assert a.evaluate(100.0, 1.0, 1, now=0.0).direction == "up"
    # still hot, but inside the cooldown window
    assert a.evaluate(100.0, 1.0, 2, now=5.0) is None
    assert a.evaluate(100.0, 1.0, 2, now=9.9) is None
    # cooldown elapsed: the sustained-hot streak fires immediately
    d = a.evaluate(100.0, 1.0, 2, now=10.0)
    assert d is not None and d.direction == "up"


def test_quiet_window_scale_down_and_min_bound():
    a = _scaler(autoscale_quiet_s=5.0, autoscale_cooldown_s=0.0,
                fleet_min_replicas=1)
    assert a.evaluate(0.0, 1.0, 2, now=0.0) is None  # quiet timer starts
    assert a.evaluate(0.0, 1.0, 2, now=4.0) is None
    d = a.evaluate(0.0, 1.0, 2, now=5.0)
    assert d is not None and d.direction == "down" and d.live == 2
    # at the floor: quiet forever, never below min
    for t in range(6, 20):
        assert a.evaluate(0.0, 1.0, 1, now=float(t)) is None


def test_queued_work_resets_quiet_timer():
    a = _scaler(autoscale_quiet_s=5.0, autoscale_cooldown_s=0.0)
    a.evaluate(0.0, 10.0, 2, now=0.0)
    # backlog below the hot threshold but non-empty: not quiet
    a.evaluate(3.0, 10.0, 2, now=4.0)
    assert a.evaluate(0.0, 10.0, 2, now=8.0) is None  # timer restarted
    assert a.evaluate(0.0, 10.0, 2, now=9.5) is None
    assert a.evaluate(0.0, 10.0, 2, now=13.0).direction == "down"


def test_autoscaler_state_dict():
    a = _scaler()
    a.evaluate(100.0, 1.0, 1, now=0.0)
    st = a.state(now=1.0)
    assert st["enabled"] and st["max_replicas"] == 4
    assert st["up_threshold_s"] == pytest.approx(5.0)
    assert st["hot_ticks"] == 1
    assert st["est_wait_s"] == pytest.approx(100.0)


# ---------------------------------------------------------------------------
# Supervisor scale-up / scale-down through the replica factory
# ---------------------------------------------------------------------------


def test_scale_up_and_bounds(engine):
    router = _mk_router(engine, n=2, fleet_max_replicas=3)
    fleet = router.fleet
    try:
        assert fleet.scale_up(signal=7.5) is True
        assert _wait_until(lambda: fleet.live_count() == 3)
        ev = router.telemetry.kinds("fleet_scale_up")
        assert ev and ev[-1]["signal"] == pytest.approx(7.5)
        assert ev[-1]["replica"] == 2 and ev[-1]["replicas"] == 3
        # at the ceiling: refused without touching the pool
        assert fleet.scale_up() is False
        assert len(fleet.replicas) == 3
        # the new replica actually serves
        code, out = _post(router.port, "/predict", _sample_json(_sample()))
        assert code == 200 and out["replica"] in (0, 1, 2)
    finally:
        router.shutdown()


def test_scale_down_zero_drop(engine):
    """Retirement drains: requests racing the scale-down all answer
    200 and the highest-index replica leaves the pool."""
    router = _mk_router(engine, n=3, fleet_max_replicas=4,
                        fleet_min_replicas=1)
    fleet = router.fleet
    try:
        results = []
        lock = threading.Lock()

        def fire(i):
            try:
                code, _ = _post(router.port, "/predict",
                                _sample_json(_sample(5, seed=i)))
            except urllib.error.HTTPError as e:
                code = e.code
            with lock:
                results.append(code)

        threads = [threading.Thread(target=fire, args=(i,))
                   for i in range(12)]
        for t in threads:
            t.start()
        assert fleet.scale_down(signal=0.1) is True
        for t in threads:
            t.join(timeout=30.0)
        assert results == [200] * 12
        assert len(fleet.replicas) == 2
        assert {r.idx for r in fleet.replicas} == {0, 1}
        ev = router.telemetry.kinds("fleet_scale_down")
        assert ev and ev[-1]["replica"] == 2 and ev[-1]["replicas"] == 2
        # below min+1 live: refused
        fleet.scale_down()
        assert fleet.scale_down() is False or len(fleet.replicas) == 1
    finally:
        router.shutdown()


def test_closed_loop_scales_up_then_down(engine):
    """The probe loop drives the whole loop: a sustained backlog signal
    grows the fleet to max, a sustained quiet window shrinks it back to
    min — each transition a health event carrying the signal."""
    router = _mk_router(engine, n=1, fleet_max_replicas=3,
                        fleet_min_replicas=1, autoscale_up_ticks=2,
                        autoscale_cooldown_s=0.0, autoscale_quiet_s=0.15)
    fleet = router.fleet
    try:
        assert fleet.autoscaler is not None and fleet.autoscaler.enabled()
        # 50 requests queued against 1 rps drain: est 50 s >> 5 s
        fleet._load_signal = lambda: (50.0, 1.0)
        assert _wait_until(lambda: fleet.live_count() == 3)
        ups = router.telemetry.kinds("fleet_scale_up")
        assert len(ups) == 2
        assert all(e["signal"] == pytest.approx(50.0) for e in ups)
        m = _get(router.port, "/metrics")
        assert m["autoscale"]["policy"]["max_replicas"] == 3
        # drained: quiet window retires back to the floor
        fleet._load_signal = lambda: (0.0, 1.0)
        assert _wait_until(lambda: fleet.live_count() == 1)
        downs = router.telemetry.kinds("fleet_scale_down")
        assert len(downs) == 2
        assert router.metrics()["router"]["errors"] == 0
    finally:
        router.shutdown()


def test_chaos_scale_fail_absorbed_by_restart(engine):
    """HYDRAGNN_CHAOS_SCALE_FAIL: the autoscaler's fresh replica dies
    the moment it joins; the backoff-restart machinery (not an inline
    retry storm) brings it back."""
    chaos = FleetChaos.from_env({"scale_fail": "1"})
    router = _mk_router(engine, n=1, fleet_chaos=chaos,
                        fleet_max_replicas=2, autoscale_up_ticks=1,
                        autoscale_cooldown_s=30.0)
    fleet = router.fleet
    try:
        fleet._load_signal = lambda: (50.0, 1.0)
        assert _wait_until(lambda: any(
            f.get("reason") == "chaos_scale_fail"
            for f in router.telemetry.kinds("replica_dead")))
        # the supervisor restarts the chaos-killed spawn under backoff
        assert _wait_until(lambda: fleet.live_count() == 2)
        assert any(f["replica"] == 1
                   for f in router.telemetry.kinds("replica_restart"))
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# Tenancy: routing, LRU, isolation
# ---------------------------------------------------------------------------


def test_tenant_routing_and_unknown_404(engine):
    router = _mk_router(engine, n=2)
    try:
        for model in (None, "ta", "tb"):
            body = _sample_json(_sample())
            if model is not None:
                body["model"] = model
            code, out = _post(router.port, "/predict", body)
            assert code == 200
            assert len(out["heads"]["energy"]) == 1
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(router.port, "/predict",
                  _sample_json(_sample(), model="nope"))
        assert ei.value.code == 404
        assert "nope" in json.loads(ei.value.read())["error"]
        # unknown tenant is terminal: no failover retries burned on it
        assert router.metrics()["router"]["failovers"] == 0
        snap = router.fleet.snapshot()
        res = snap["replicas"][0]["tenants_resident"]
        assert res[0] == DEFAULT_TENANT and set(res[1:]) <= {"ta", "tb"}
    finally:
        router.shutdown()


def test_tenant_lru_eviction_recompiles_nothing(engine):
    """max_tenants=2 leaves ONE extra resident slot: touching ta then
    tb evicts ta (tenant_evict), re-touching ta re-admits it — and the
    shared fork cache means the whole dance compiles nothing."""
    misses_before = engine.cache_stats()["misses"]
    router = _mk_router(engine, n=1, max_tenants=2, tel_replicas=True)
    try:
        for model in ("ta", "tb", "ta"):
            code, _ = _post(router.port, "/predict",
                            _sample_json(_sample(), model=model))
            assert code == 200
        snap = router.fleet.snapshot()["replicas"][0]
        assert snap["tenant_evictions"] >= 2
        assert snap["tenants_resident"] == [DEFAULT_TENANT, "ta"]
        ev = router.telemetry.kinds("tenant_evict")
        assert [e["tenant"] for e in ev][:2] == ["ta", "tb"]
        assert engine.cache_stats()["misses"] == misses_before
    finally:
        router.shutdown()


def test_chaos_hot_tenant_sheds_only_that_tenant(engine):
    """HYDRAGNN_CHAOS_TENANT_HOT marks tb hot from tick 1 on: tb gets
    429 + Retry-After, the default tenant and ta keep serving 200."""
    chaos = FleetChaos.from_env({"tenant_hot": "1+:tb"})
    router = _mk_router(engine, n=2, fleet_chaos=chaos)
    try:
        assert _wait_until(lambda: "tb" in router.fleet.hot_tenants)
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(router.port, "/predict",
                  _sample_json(_sample(), model="tb"))
        assert ei.value.code == 429
        assert ei.value.headers.get("Retry-After") is not None
        for model in (None, "ta"):
            body = _sample_json(_sample())
            if model is not None:
                body["model"] = model
            code, _ = _post(router.port, "/predict", body)
            assert code == 200
        m = router.metrics()
        assert m["tenancy"]["hot"] == ["tb"]
        assert m["tenancy"]["per_tenant"]["tb"]["shed_429"] >= 1
        assert m["tenancy"]["per_tenant"]["ta"]["shed_429"] == 0
        sheds = router.telemetry.kinds("tenant_shed")
        assert sheds and all(f["reason"] == "chaos_hot" for f in sheds)
    finally:
        router.shutdown()


def test_tenant_budget_shed_isolates(engine):
    """Per-tenant admission budget: cap = ceil(frac * drain_rate *
    deadline).  A tenant over its outstanding cap sheds 429
    (reason=budget) while the other tenants' traffic is untouched."""
    router = _mk_router(engine, n=1, tenant_budget_frac=0.04)
    fleet = router.fleet
    try:
        # pin the measured drain rate the cap derives from (the probe
        # loop caches whatever _load_signal reports)
        fleet._load_signal = lambda: (0.0, 5.0)
        assert _wait_until(lambda: fleet.last_drain_rate == 5.0)
        # cap = ceil(0.04 * 5 rps * 10 s) = 2; saturate tb's slots
        with router._lock:
            router._tenant_out["tb"] = 2
            router._per_tenant["tb"] = {
                "requests": 0, "responses_200": 0, "shed_429": 0}
        with pytest.raises(urllib.error.HTTPError) as ei:
            _post(router.port, "/predict",
                  _sample_json(_sample(), model="tb"))
        assert ei.value.code == 429
        code, _ = _post(router.port, "/predict", _sample_json(_sample()))
        assert code == 200
        shed = router.telemetry.kinds("tenant_shed")
        assert shed and shed[-1]["reason"] == "budget"
        assert shed[-1]["cap"] == 2 and shed[-1]["outstanding"] == 2
        # cold start never caps: no drain sample -> no shed
        fleet.last_drain_rate = 0.0
        fleet._load_signal = lambda: (0.0, 0.0)
        assert router._tenant_cap(10.0) is None
    finally:
        router.shutdown()


def test_tenant_failover_after_replica_kill(engine):
    """A tenant request rides the same failover ladder: kill the
    replica mid-fleet and tenant traffic lands on the survivor."""
    router = _mk_router(engine, n=2)
    fleet = router.fleet
    try:
        victim = fleet.replicas[0]
        victim.kill()
        fleet.mark_dead(victim, reason="probe_dead")
        for i in range(4):
            code, out = _post(router.port, "/predict",
                              _sample_json(_sample(5, seed=i), model="ta"))
            assert code == 200 and out["replica"] == 1
    finally:
        router.shutdown()


# ---------------------------------------------------------------------------
# Engine executable LRU
# ---------------------------------------------------------------------------


def test_executable_lru_eviction():
    """max_resident_executables=1 with a 2-bucket ladder: the second
    warmup compile evicts the first (executable_evict), and re-touching
    the evicted bucket is a counted recompile."""
    import jax

    cfg = ModelConfig(
        model_type="SAGE", input_dim=1, hidden_dim=8, output_dim=(1,),
        output_type=("graph",), graph_head=GraphHeadCfg(1, 8, 1, (8,)),
        node_head=None, task_weights=(1.0,), num_conv_layers=2)
    model = create_model(cfg)
    pads = [PadSpec.for_batch(2, 16, 64), PadSpec.for_batch(4, 16, 64)]
    example = collate([_sample()], pads[0], _HEADS)
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        example, train=False)
    state = InferenceState(step=0, params=variables["params"],
                           batch_stats=variables.get("batch_stats", {}))
    tel = _Tel()
    eng = InferenceEngine(cfg, state, _HEADS, pads,
                          serving=ServingConfig(
                              port=0, max_resident_executables=1),
                          telemetry=tel)
    eng.warmup()
    stats = eng.cache_stats()
    # compile b0 -> compile b1 (evicts b0) -> golden replay recompiles
    # b0 (evicts b1): a cap below one bucket ladder thrashes, exactly
    # what docs/SERVING.md warns about
    assert stats["evictions"] == 2
    ev = tel.kinds("executable_evict")
    assert len(ev) == 2 and all(e["cap"] == 1 for e in ev)
    assert [e["graphs"] for e in ev] == [pads[0].num_graphs,
                                         pads[1].num_graphs]
    # the smallest bucket is resident (the golden replay compiled it
    # last); touching the other is a counted recompile + eviction
    eng._executable(pads[1])
    s2 = eng.cache_stats()
    assert s2["misses"] == stats["misses"] + 1
    assert s2["evictions"] == 3
    eng._executable(pads[1])
    assert eng.cache_stats()["hits"] == s2["hits"] + 1


def test_unbounded_cache_never_evicts(engine):
    assert engine.cache_stats()["evictions"] == 0


# ---------------------------------------------------------------------------
# Knob plumbing: config section, env overlays, validation, chaos specs
# ---------------------------------------------------------------------------


def test_config_section_and_env_overlays(monkeypatch):
    cfg = ServingConfig.from_section({
        "port": 0, "fleet_min_replicas": 2, "fleet_max_replicas": 5,
        "autoscale_up_frac": 0.25, "autoscale_up_ticks": 7,
        "autoscale_quiet_s": 12.0, "autoscale_cooldown_s": 3.0,
        "max_tenants": 8, "tenant_budget_frac": 0.5,
        "max_resident_executables": 6})
    assert (cfg.fleet_min_replicas, cfg.fleet_max_replicas) == (2, 5)
    assert cfg.autoscale_up_frac == 0.25 and cfg.autoscale_up_ticks == 7
    assert cfg.max_tenants == 8 and cfg.max_resident_executables == 6
    monkeypatch.setenv("HYDRAGNN_SERVE_FLEET_MIN", "3")
    monkeypatch.setenv("HYDRAGNN_SERVE_FLEET_MAX", "9")
    monkeypatch.setenv("HYDRAGNN_SERVE_AUTOSCALE_UP_TICKS", "2")
    monkeypatch.setenv("HYDRAGNN_SERVE_MAX_TENANTS", "2")
    monkeypatch.setenv("HYDRAGNN_SERVE_TENANT_BUDGET_FRAC", "0.1")
    monkeypatch.setenv("HYDRAGNN_SERVE_MAX_EXECUTABLES", "4")
    cfg = ServingConfig.from_section({"port": 0})
    assert (cfg.fleet_min_replicas, cfg.fleet_max_replicas) == (3, 9)
    assert cfg.autoscale_up_ticks == 2 and cfg.max_tenants == 2
    assert cfg.tenant_budget_frac == 0.1
    assert cfg.max_resident_executables == 4


def test_config_validation():
    with pytest.raises(ValueError, match="fleet_min_replicas"):
        ServingConfig(port=0, fleet_min_replicas=4, fleet_max_replicas=2)
    with pytest.raises(ValueError):
        ServingConfig(port=0, autoscale_up_frac=-0.1)
    with pytest.raises(ValueError):
        ServingConfig(port=0, max_tenants=0)
    # min <= max only enforced when the autoscaler is armed
    ServingConfig(port=0, fleet_min_replicas=4, fleet_max_replicas=0)


def test_fleet_chaos_tenant_specs(monkeypatch):
    chaos = FleetChaos.from_env({"tenant_hot": "2:tb", "scale_fail": "1"})
    assert chaos.on_probe() == [("scale_fail", None)]
    assert chaos.on_probe() == [("tenant_hot", "tb")]
    assert chaos.on_probe() == []
    monkeypatch.setenv("HYDRAGNN_CHAOS_TENANT_HOT", "1+")
    chaos = FleetChaos.from_env(None)
    # env wins; no name after the colon targets the default tenant
    assert chaos.on_probe() == [("tenant_hot", None)]
    assert chaos.on_probe() == [("tenant_hot", None)]


def test_default_tenant_shed_maps_to_429(engine):
    """RequestShedError from the tenant gate carries retry_after_s like
    the batcher's admission shed."""
    router = _mk_router(engine, n=1)
    try:
        router.fleet.hot_tenants = {"ta"}
        with pytest.raises(RequestShedError) as ei:
            router._admit_tenant("ta", 10.0)
        assert ei.value.retry_after_s >= 1.0
    finally:
        router.shutdown()
