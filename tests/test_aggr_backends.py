"""Aggregation-backend equivalence: scatter vs onehot vs pallas.

The onehot/pallas backends must be drop-in replacements for XLA scatter in
``graph/segment.py:segment_sum`` — same forward values, same gradients, same
silent dropping of out-of-range segment ids (how padded edges/triplets are
discarded).  Pallas runs in interpreter mode off-TPU, so this exercises the
real kernel logic on the CPU CI mesh.
"""

import numpy as np
import pytest

import jax
import jax.numpy as jnp

from hydragnn_tpu.graph import segment
from hydragnn_tpu.ops.aggregate import segment_sum_onehot, segment_sum_pallas

BACKENDS = {
    "onehot": segment_sum_onehot,
    "pallas": segment_sum_pallas,
}


def _case(e=70, n=13, f=5, seed=0, oob=True):
    rng = np.random.RandomState(seed)
    data = rng.randn(e, f).astype(np.float32)
    ids = rng.randint(0, n, size=e)
    if oob:  # padded edges scatter out of range and must vanish
        ids[-7:] = n + rng.randint(0, 3, size=7)
    return jnp.asarray(data), jnp.asarray(ids), n


@pytest.mark.parametrize("backend", sorted(BACKENDS))
def test_forward_matches_scatter(backend):
    data, ids, n = _case()
    want = jax.ops.segment_sum(data, ids, n)
    got = BACKENDS[backend](data, ids, n)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("backend", sorted(BACKENDS))
def test_gradient_matches_scatter(backend):
    data, ids, n = _case(seed=1)
    w = jnp.asarray(np.random.RandomState(2).randn(n, data.shape[1]),
                    jnp.float32)

    def loss(fn):
        return lambda d: jnp.sum(fn(d, ids, n) * w)

    g_want = jax.grad(loss(jax.ops.segment_sum))(data)
    g_got = jax.grad(loss(BACKENDS[backend]))(data)
    np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("backend", sorted(BACKENDS))
def test_jit_and_1d(backend):
    data, ids, n = _case(e=40, f=1, seed=3)
    data1d = data[:, 0]
    want = jax.ops.segment_sum(data1d, ids, n)
    got = jax.jit(BACKENDS[backend], static_argnums=2)(data1d, ids, n)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("backend", sorted(BACKENDS))
def test_bf16_inputs(backend):
    """bf16 messages accumulate in f32 and come back as bf16."""
    data, ids, n = _case(seed=6)
    got = BACKENDS[backend](data.astype(jnp.bfloat16), ids, n)
    assert got.dtype == jnp.bfloat16
    want = jax.ops.segment_sum(data.astype(jnp.bfloat16), ids, n)
    np.testing.assert_allclose(
        np.asarray(got, np.float32), np.asarray(want, np.float32),
        rtol=0.05, atol=0.05)


def _sorted_case(n=600, max_deg=12, f=7, seed=0):
    rng = np.random.RandomState(seed)
    counts = rng.randint(0, max_deg + 1, size=n)
    ids = np.repeat(np.arange(n), counts)
    data = rng.randn(len(ids), f).astype(np.float32)
    return jnp.asarray(data), jnp.asarray(ids), n, max_deg


@pytest.mark.parametrize("n", [600, 2500])  # 2500 spans >2 node blocks of 1024
def test_sorted_forward_and_grad_match_scatter(n):
    from hydragnn_tpu.ops.aggregate import segment_sum_sorted

    data, ids, n, k = _sorted_case(n=n)
    want = jax.ops.segment_sum(data, ids, n)
    got = segment_sum_sorted(data, ids, n, k)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)
    w = jnp.asarray(np.random.RandomState(1).randn(n, data.shape[1]),
                    jnp.float32)
    g_want = jax.grad(
        lambda d: jnp.sum(jax.ops.segment_sum(d, ids, n) * w))(data)
    g_got = jax.grad(
        lambda d: jnp.sum(segment_sum_sorted(d, ids, n, k) * w))(data)
    np.testing.assert_allclose(np.asarray(g_got), np.asarray(g_want),
                               rtol=1e-5, atol=1e-5)


def test_sorted_on_collated_receivers():
    """The real invariant source: collate's receivers with a padded tail."""
    from hydragnn_tpu.graph.batch import GraphSample, HeadSpec, PadSpec, collate
    from hydragnn_tpu.graph.neighborlist import radius_graph
    from hydragnn_tpu.ops.aggregate import segment_sum_sorted

    rng = np.random.RandomState(2)
    samples = []
    for _ in range(6):
        pos = rng.rand(10, 3).astype(np.float32) * 2.5
        samples.append(GraphSample(
            x=rng.rand(10, 1).astype(np.float32), pos=pos,
            edge_index=radius_graph(pos, 1.3, 8),
            graph_y=rng.rand(1).astype(np.float32)))
    b = collate(samples, PadSpec.for_batch(6, 12, 90),
                [HeadSpec("e", "graph", 1)])
    data = jnp.asarray(
        rng.randn(b.num_edges, 5).astype(np.float32)) * b.edge_mask[:, None]
    want = jax.ops.segment_sum(data, b.receivers, b.num_nodes)
    got = segment_sum_sorted(data, b.receivers, b.num_nodes, 8)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("backend", ["onehot", "pallas"])
def test_env_knob_dispatch(backend, monkeypatch):
    """segment.segment_sum honors HYDRAGNN_AGGR_BACKEND, including masks.

    Un-jitted calls read the knob per trace; the baseline is computed with
    the knob removed so a pre-set shell env can't make this vacuous."""
    data, ids, n = _case(seed=4, oob=False)
    mask = jnp.asarray(
        np.random.RandomState(5).rand(data.shape[0]) > 0.3, jnp.float32)
    monkeypatch.delenv("HYDRAGNN_AGGR_BACKEND", raising=False)
    want = segment.segment_sum(data, ids, n, mask)
    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", backend)
    got = segment.segment_sum(data, ids, n, mask)
    np.testing.assert_allclose(np.asarray(got), np.asarray(want),
                               rtol=1e-5, atol=1e-5)


@pytest.mark.parametrize("backend", ["onehot", "pallas"])
def test_model_forward_under_backend(backend, monkeypatch):
    """A whole SchNet forward+grad agrees across aggregation backends."""
    from hydragnn_tpu.graph.batch import GraphSample, HeadSpec, PadSpec, collate
    from hydragnn_tpu.graph.neighborlist import radius_graph
    from hydragnn_tpu.models.base import GraphHeadCfg, ModelConfig
    from hydragnn_tpu.models.create import create_model

    rng = np.random.RandomState(0)
    samples = []
    for _ in range(4):
        pos = rng.rand(9, 3).astype(np.float32) * 2.5
        ei = radius_graph(pos, 1.2, max_neighbours=8)
        samples.append(GraphSample(
            x=rng.randint(0, 3, (9, 1)).astype(np.float32), pos=pos,
            edge_index=ei, graph_y=rng.rand(1).astype(np.float32)))
    batch = collate(samples, PadSpec.for_batch(4, 12, 40),
                    [HeadSpec("e", "graph", 1)])
    cfg = ModelConfig(
        model_type="SchNet", input_dim=1, hidden_dim=16,
        output_dim=(1,), output_type=("graph",),
        graph_head=GraphHeadCfg(1, 16, 1, (16,)), node_head=None,
        task_weights=(1.0,), num_conv_layers=2, num_gaussians=8,
        num_filters=16, radius=1.2, max_neighbours=8)
    model = create_model(cfg)
    params = model.init(jax.random.PRNGKey(0), batch, train=False)

    def fwd():
        out = model.apply(params, batch, train=False)
        return float(jnp.sum(out[0] * batch.graph_mask[:, None]))

    monkeypatch.delenv("HYDRAGNN_AGGR_BACKEND", raising=False)
    want = fwd()
    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", backend)
    got = fwd()
    assert abs(got - want) < 1e-3, (got, want)
