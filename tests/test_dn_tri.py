"""Parity tests for the fused DimeNet triplet-interaction kernel
(ops/dn_tri.py): forward + all gradients vs the composed XLA math,
interpret mode on CPU, with realistic sorted/masked triplet tables."""

import numpy as np
import jax
import jax.numpy as jnp

from hydragnn_tpu.graph.batch import GraphSample, HeadSpec, PadSpec, collate
from hydragnn_tpu.graph.neighborlist import radius_graph
from hydragnn_tpu.models.dimenet import add_dimenet_extras, count_triplets
from hydragnn_tpu.ops.dn_tri import dimenet_triplet_mp

G1, B, D = 21, 8, 16  # S*R (7x3), basis_emb, int_emb
S, R = 7, 3


def _tables(n_graphs=5, nodes=7, seed=0, extra_pad=37):
    rng = np.random.RandomState(seed)
    samples = []
    for _ in range(n_graphs):
        pos = rng.rand(nodes, 3).astype(np.float32) * 2.0
        samples.append(GraphSample(
            x=rng.rand(nodes, 1).astype(np.float32), pos=pos,
            edge_index=radius_graph(pos, 1.3, 6),
            graph_y=rng.rand(1).astype(np.float32)))
    pad = PadSpec.for_batch(n_graphs, nodes,
                            max(s.num_edges for s in samples))
    batch = collate(samples, pad, [HeadSpec("e", "graph", 1)])
    real = np.asarray(batch.edge_mask) > 0
    ei = np.stack([np.asarray(batch.senders)[real],
                   np.asarray(batch.receivers)[real]])
    t = count_triplets(ei, batch.x.shape[0])
    batch = add_dimenet_extras(batch, max_triplets=t + extra_pad)
    return batch


def _inputs(batch, seed=1):
    rng = np.random.RandomState(seed)
    e = batch.senders.shape[0]
    radial = jnp.asarray(rng.rand(e, G1), jnp.float32)
    x2 = jnp.asarray(rng.randn(e, D), jnp.float32)
    t = batch.extras["dn_idx_kj"].shape[0]
    cbf = jnp.asarray(rng.randn(t, S) * 0.5, jnp.float32)
    w1 = jnp.asarray(rng.randn(G1, B) * 0.3, jnp.float32)
    w2 = jnp.asarray(rng.randn(B, D) * 0.3, jnp.float32)
    return radial, x2, cbf, w1, w2


def _composed(radial, x2, cbf, w1, w2, idx_kj, idx_ji, tmask, e):
    sbf = radial[idx_kj] * jnp.repeat(cbf, R, axis=1)
    emb = (sbf @ w1) @ w2
    msg = x2[idx_kj] * emb * tmask[:, None]
    return jax.ops.segment_sum(msg, idx_ji, num_segments=e)


def test_forward_matches_composed():
    batch = _tables()
    radial, x2, cbf, w1, w2 = _inputs(batch)
    kj = jnp.asarray(batch.extras["dn_idx_kj"])
    ji = jnp.asarray(batch.extras["dn_idx_ji"])
    tm = jnp.asarray(batch.extras["dn_triplet_mask"])
    pk = jnp.asarray(batch.extras["dn_perm_kj"])
    out = dimenet_triplet_mp(radial, x2, cbf, w1, w2, kj, ji,
                             tm.astype(jnp.int32), pk, R)
    ref = _composed(radial, x2, cbf, w1, w2, kj, ji, tm,
                    x2.shape[0])
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=2e-5, atol=2e-5)


def test_gradients_match_composed():
    batch = _tables(seed=3)
    radial, x2, cbf, w1, w2 = _inputs(batch, seed=4)
    kj = jnp.asarray(batch.extras["dn_idx_kj"])
    ji = jnp.asarray(batch.extras["dn_idx_ji"])
    tm = jnp.asarray(batch.extras["dn_triplet_mask"])
    pk = jnp.asarray(batch.extras["dn_perm_kj"])
    e = x2.shape[0]
    rng = np.random.RandomState(7)
    wmat = jnp.asarray(rng.randn(e, D), jnp.float32)

    def loss_fused(args):
        out = dimenet_triplet_mp(*args, kj, ji, tm.astype(jnp.int32),
                                 pk, R)
        return jnp.sum(out * wmat)

    def loss_ref(args):
        out = _composed(*args, kj, ji, tm, e)
        return jnp.sum(out * wmat)

    inputs = (radial, x2, cbf, w1, w2)
    gf = jax.grad(loss_fused)(inputs)
    gr = jax.grad(loss_ref)(inputs)
    tmask = np.asarray(tm).astype(bool)
    for name, a, b in zip(("radial", "x2", "cbf", "w1", "w2"), gf, gr):
        a, b = np.asarray(a), np.asarray(b)
        if name == "cbf":
            # masked triplets: exactly zero from the fused path (their
            # blocks are schedule-skipped)
            assert np.all(a[~tmask] == 0.0)
            a, b = a[tmask], b[tmask]
        np.testing.assert_allclose(a, b, rtol=3e-4, atol=3e-4,
                                   err_msg=name)


def test_empty_and_all_masked():
    batch = _tables(seed=5)
    radial, x2, cbf, w1, w2 = _inputs(batch, seed=6)
    kj = jnp.asarray(batch.extras["dn_idx_kj"])
    ji = jnp.asarray(batch.extras["dn_idx_ji"])
    pk = jnp.asarray(batch.extras["dn_perm_kj"])
    tm0 = jnp.zeros_like(jnp.asarray(batch.extras["dn_triplet_mask"]))
    out = dimenet_triplet_mp(radial, x2, cbf, w1, w2, kj, ji,
                             tm0.astype(jnp.int32), pk, R)
    np.testing.assert_allclose(np.asarray(out), 0.0, atol=1e-6)


def test_model_level_kernel_equals_composed(monkeypatch):
    """DIMEStack with the factored-basis kernel on vs off: identical
    param tree (_DenseParams mirrors the nn.Dense layers), matching
    forward and param grads."""
    import os

    import dataclasses
    from hydragnn_tpu.models.base import GraphHeadCfg, ModelConfig
    from hydragnn_tpu.models.create import create_model

    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", "fused")
    batch_on = _tables(seed=8)
    assert "dn_tri_ok" in batch_on.extras
    monkeypatch.setenv("HYDRAGNN_DN_TRI_OFF", "1")
    batch_off = _tables(seed=8)
    assert "dn_tri_ok" not in batch_off.extras
    monkeypatch.delenv("HYDRAGNN_DN_TRI_OFF")

    cfg = ModelConfig(
        model_type="DimeNet", input_dim=1, hidden_dim=16, output_dim=(1,),
        output_type=("graph",), graph_head=GraphHeadCfg(1, 8, 1, (8,)),
        node_head=None, task_weights=(1.0,), num_conv_layers=2,
        radius=1.3, max_neighbours=6, envelope_exponent=5,
        num_before_skip=1, num_after_skip=1, num_radial=3,
        num_spherical=7, basis_emb_size=8, int_emb_size=16,
        out_emb_size=16)
    model = create_model(cfg)
    variables = model.init({"params": jax.random.PRNGKey(0)}, batch_on,
                           train=False)

    def loss(params, batch):
        out = model.apply({"params": params}, batch, train=False)
        return sum(jnp.sum(o * o) for o in out)

    l_on = loss(variables["params"], batch_on)
    l_off = loss(variables["params"], batch_off)
    np.testing.assert_allclose(float(l_on), float(l_off), rtol=2e-5)

    g_on = jax.grad(lambda p: loss(p, batch_on))(variables["params"])
    g_off = jax.grad(lambda p: loss(p, batch_off))(variables["params"])
    flat_on = jax.tree_util.tree_leaves_with_path(g_on)
    flat_off = dict(jax.tree_util.tree_leaves_with_path(g_off))
    assert flat_on
    for path, leaf in flat_on:
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat_off[path]), rtol=5e-4,
            atol=5e-4, err_msg=str(path))


def test_dn_tri_gate_static_and_sticky(monkeypatch):
    """DnTriGate: static mode decides once from the dataset bound with no
    per-batch measurement; sticky mode falls back for the whole run on the
    first over-span batch (ADVICE: dn_tri_ok marker instability)."""
    from hydragnn_tpu.models.dimenet import DnTriGate
    from hydragnn_tpu.ops.fused_mp import _NODE_BLOCK

    def must_not_measure():
        raise AssertionError("static gate measured a batch span")

    # static: small bound -> always ok, and never measures
    small = DnTriGate(max_edges_per_graph=2 * _NODE_BLOCK)
    assert small.static and small.allow(must_not_measure)
    assert small.allow(must_not_measure)  # stable across batches
    # static: a bound spanning > 2 blocks at worst alignment -> always off
    big = DnTriGate(max_edges_per_graph=4 * _NODE_BLOCK)
    assert not big.allow(must_not_measure)

    # sticky: first over-span disables the marker for the rest of the run
    gate = DnTriGate()
    assert gate.allow(lambda: 1)
    assert not gate.allow(lambda: 3)
    assert not gate.allow(lambda: 0)  # stays off: whole-run fallback
    assert not gate.allow(must_not_measure)  # and stops measuring


def test_dn_tri_gate_marker_consistency(monkeypatch):
    """With a static gate every batch carries the same extras tree even if
    an individual batch would have over-spanned the per-batch check."""
    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", "fused")
    from hydragnn_tpu.models.dimenet import DnTriGate

    def raw_batch(seed):
        rng = np.random.RandomState(seed)
        samples = []
        for _ in range(5):
            pos = rng.rand(7, 3).astype(np.float32) * 2.0
            samples.append(GraphSample(
                x=rng.rand(7, 1).astype(np.float32), pos=pos,
                edge_index=radius_graph(pos, 1.3, 6),
                graph_y=rng.rand(1).astype(np.float32)))
        pad = PadSpec.for_batch(5, 7, max(s.num_edges for s in samples))
        return collate(samples, pad, [HeadSpec("e", "graph", 1)])

    gate = DnTriGate(max_edges_per_graph=42)
    b1 = add_dimenet_extras(raw_batch(21), max_triplets=4096, tri_gate=gate)
    b2 = add_dimenet_extras(raw_batch(22), max_triplets=4096, tri_gate=gate)
    assert ("dn_tri_ok" in b1.extras) == ("dn_tri_ok" in b2.extras)
    assert sorted(b1.extras) == sorted(b2.extras)
