"""PBC radius-graph unit tests (parity: reference
tests/test_periodic_boundary_conditions.py:25-123): exact neighbor counts on
an H2 molecule and a bulk-BCC Cr supercell, with and without self loops,
positions untouched."""

import numpy as np

from hydragnn_tpu.graph.neighborlist import radius_graph, radius_graph_pbc


def _check_pbc(pos, cell, radius, expected_neighbors,
               expected_neighbors_self_loops):
    n = pos.shape[0]
    ei_no_loop, lengths = radius_graph_pbc(
        pos, cell, radius, max_neighbours=100000, loop=False)
    ei_loop, _ = radius_graph_pbc(
        pos, cell, radius, max_neighbours=100000, loop=True)

    assert ei_no_loop.shape[1] == expected_neighbors * n
    assert ei_loop.shape[1] == expected_neighbors_self_loops * n
    # all edge lengths within the radius
    assert (lengths <= radius + 1e-9).all()


def test_periodic_h2():
    # H2 in a 3x3x3 cell: 1 bond/atom without loops, +1 self edge with loops
    cell = np.eye(3) * 3.0
    pos = np.array([[1.0, 1.0, 1.0], [1.43, 1.43, 1.43]])
    _check_pbc(pos, cell, radius=0.9, expected_neighbors=1,
               expected_neighbors_self_loops=2)


def test_periodic_bcc_large():
    # 5x5x5 orthorhombic BCC Cr supercell (a=3.6): radius 5.0 captures the
    # first (8 at a*sqrt(3)/2) and second (6 at a) neighbor shells = 14.
    a = 3.6
    reps = 5
    base = np.array([[0.0, 0.0, 0.0], [0.5, 0.5, 0.5]]) * a
    pos = []
    for i in range(reps):
        for j in range(reps):
            for k in range(reps):
                pos.append(base + np.array([i, j, k]) * a)
    pos = np.concatenate(pos, axis=0)
    cell = np.eye(3) * (a * reps)
    _check_pbc(pos, cell, radius=5.0, expected_neighbors=14,
               expected_neighbors_self_loops=15)


def test_pbc_duplicate_edge_rejection():
    # A cell small enough that an atom pair connects both directly and
    # through an image must raise (parity: reference RadiusGraphPBC assert,
    # hydragnn/preprocess/utils.py:160-171).
    cell = np.eye(3) * 1.0
    pos = np.array([[0.0, 0.0, 0.0], [0.5, 0.5, 0.5]])
    import pytest

    with pytest.raises(ValueError, match="duplicate"):
        radius_graph_pbc(pos, cell, radius=1.2, loop=False)


def test_open_vs_pbc_small_radius():
    # With a radius smaller than any image distance, PBC and open-boundary
    # graphs coincide.
    rng = np.random.RandomState(0)
    pos = rng.rand(8, 3) * 2.0 + 4.0
    cell = np.eye(3) * 10.0
    ei_open = radius_graph(pos, radius=1.5, max_neighbours=100)
    ei_pbc, _ = radius_graph_pbc(pos, cell, radius=1.5, loop=False)
    open_set = set(zip(ei_open[0].tolist(), ei_open[1].tolist()))
    pbc_set = set(zip(ei_pbc[0].tolist(), ei_pbc[1].tolist()))
    assert open_set == pbc_set
