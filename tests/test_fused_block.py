"""One parity harness for the fused message-passing stack — every arch in
models/create.py:ALL_ARCHS against the composed XLA twin, plus kernel-level
parity for each spec on the fused-block builder (ops/fused_block.py) and
the shared schedule kernels it grew out of.

Collapses the former per-kernel suites (test_poly_mp.py, test_egcl_mp.py,
test_fused_mp.py) onto one file: a newly registered arch lands in the
model-level parametrization automatically, and a new builder spec adds a
kernel-level section here rather than a new test file.

Sections:
  1. model-level fused-vs-scatter parity, parametrized over ALL_ARCHS
  2. poly multi-moment kernels (ops/poly_mp.py): PNA/MFC/SAGE moments
  3. EGCL interaction-block spec (ops/egcl_mp.py on the builder)
  4. CGCNN gated-sum spec (ops/cgcnn_mp.py on the builder)
  5. DimeNet triplet paths: legacy W-window and the builder-backed
     wide-dim route
  6. gather-mul / dense segment-sum schedule kernels (ops/fused_mp.py)
  7. collate invariants + trace-time dispatch tally

Interpret mode on CPU, production collate invariants throughout.
"""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from hydragnn_tpu.graph import segment
from hydragnn_tpu.graph.batch import GraphSample, HeadSpec, PadSpec, collate
from hydragnn_tpu.graph.neighborlist import radius_graph
from hydragnn_tpu.models.base import GraphHeadCfg, ModelConfig
from hydragnn_tpu.models.create import ALL_ARCHS, create_model
from hydragnn_tpu.ops.egcl_mp import egcl_block
from hydragnn_tpu.ops.fused_mp import gather_mul_segment_sum
from hydragnn_tpu.ops.poly_mp import gather_poly_segment, segment_poly_dense

_BIG = 1e9
ALL_MOMENTS = ("sum", "sq", "mxmn", "cnt")


# ---------------------------------------------------------------------------
# shared batch builders
# ---------------------------------------------------------------------------


def _batch(n_graphs=24, max_nodes=16, seed=0, max_neigh=10):
    rng = np.random.RandomState(seed)
    samples = []
    for _ in range(n_graphs):
        n = int(rng.randint(3, max_nodes + 1))
        pos = rng.rand(n, 3).astype(np.float32) * 2.5
        x = rng.rand(n, 2).astype(np.float32)
        ei = radius_graph(pos, 1.4, max_neigh)
        samples.append(GraphSample(x=x, pos=pos, edge_index=ei,
                                   graph_y=np.ones(1, np.float32), node_y=x))
    pad = PadSpec.for_batch(n_graphs, max_nodes, max_nodes * max_neigh)
    return collate(samples, pad, [HeadSpec("e", "graph", 1)])


def _edge_data(b, f=48, seed=1, quantize=False):
    rng = np.random.RandomState(seed)
    e = b.senders.shape[0]
    data = rng.randn(e, f).astype(np.float32)
    if quantize:
        # coarse grid -> deliberate within-segment ties, exercising the
        # even tie-split of the max/min gradient
        data = np.round(data * 2.0) / 2.0
    return jnp.asarray(data)


def _sender_perm(b):
    return jnp.asarray(np.argsort(np.asarray(b.senders), kind="stable"),
                       jnp.int32)


# ---------------------------------------------------------------------------
# 1. model-level parity: every arch, fused backend vs composed scatter
# ---------------------------------------------------------------------------

# one seed per arch, kept from the per-arch suites this file collapsed so
# the graphs (and any historically tuned tolerances) are unchanged
_ARCH_SEED = {"SchNet": 5, "DimeNet": 13}


def _model_cfg(model_type):
    kw = dict(
        model_type=model_type, input_dim=1,
        # CGCNN's conv is dim-preserving: hidden_dim forced = input_dim
        hidden_dim=1 if model_type == "CGCNN" else 16,
        output_dim=(1,), output_type=("graph",),
        graph_head=GraphHeadCfg(1, 16, 1, (16,)), node_head=None,
        task_weights=(1.0,), num_conv_layers=2,
        max_degree=16, max_neighbours=16,
        pna_avg_deg_log=1.1, pna_avg_deg_lin=3.0)
    if model_type == "SchNet":
        kw.update(num_gaussians=8, num_filters=16, radius=1.4,
                  max_neighbours=10)
    elif model_type == "DimeNet":
        kw.update(hidden_dim=8, graph_head=GraphHeadCfg(1, 8, 1, (8,)),
                  basis_emb_size=4, envelope_exponent=5, int_emb_size=4,
                  out_emb_size=4, num_after_skip=1, num_before_skip=1,
                  num_radial=4, num_spherical=3, radius=1.4,
                  max_neighbours=10)
    elif model_type == "EGNN":
        kw.update(equivariance=True, radius=1.4, max_neighbours=10)
    return ModelConfig(**kw)


def _model_batch(model_type, seed):
    b = _batch(seed=seed)
    if model_type == "DimeNet":
        from hydragnn_tpu.models.dimenet import add_dimenet_extras

        b = add_dimenet_extras(b, max_triplets=4096)
    return b


@pytest.mark.parametrize("model_type", ALL_ARCHS)
def test_model_fused_matches_scatter(model_type, monkeypatch):
    """Full forward + param grads under HYDRAGNN_AGGR_BACKEND=fused must
    match the composed scatter path for EVERY registered arch — the
    kernels are exact, not approximate.  (bench.py's sweep derives from
    the same ALL_ARCHS list, so a new arch lands in both at once.)"""
    cfg = _model_cfg(model_type)
    model = create_model(cfg)
    seed = _ARCH_SEED.get(model_type, 9)

    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", "fused")
    b_fused = _model_batch(model_type, seed)
    assert "edge_perm_sender" in b_fused.extras
    v = model.init({"params": jax.random.PRNGKey(0),
                    "dropout": jax.random.PRNGKey(1)}, b_fused, train=False)

    def loss(params, b):
        out = model.apply({"params": params,
                           "batch_stats": v.get("batch_stats", {})},
                          b, train=False)
        return jnp.sum(out[0] ** 2)

    lf = float(loss(v["params"], b_fused))
    gf = jax.grad(loss)(v["params"], b_fused)

    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", "scatter")
    b_plain = _model_batch(model_type, seed)
    lp = float(loss(v["params"], b_plain))
    gp = jax.grad(loss)(v["params"], b_plain)

    assert abs(lf - lp) < 1e-4 * max(1.0, abs(lp))
    for a, c in zip(jax.tree_util.tree_leaves(gf),
                    jax.tree_util.tree_leaves(gp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# 2. poly multi-moment kernels (ops/poly_mp.py)
# ---------------------------------------------------------------------------


def _refs(data, ids, mask, n):
    """Composed-path moments with the production masking conventions."""
    dm = data * mask[:, None]
    cat = jnp.concatenate([data, -data], axis=1)
    cat = jnp.where(mask[:, None] > 0, cat, -_BIG)
    mxmn = jax.ops.segment_max(cat, ids, num_segments=n)
    return {
        "sum": jax.ops.segment_sum(dm, ids, num_segments=n),
        "sq": jax.ops.segment_sum(dm * dm, ids, num_segments=n),
        "mxmn": mxmn,
        "cnt": jax.ops.segment_sum(mask, ids, num_segments=n),
    }


def test_scatter_forward_all_moments():
    b = _batch()
    data = _edge_data(b)
    ids, mask = jnp.asarray(b.receivers), jnp.asarray(b.edge_mask)
    n = b.x.shape[0]
    outs = segment_poly_dense(data, ids, n, ALL_MOMENTS, valid=mask)
    ref = _refs(data, ids, mask, n)
    np.testing.assert_allclose(outs[0], ref["sum"], rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(outs[1], ref["sq"], rtol=1e-5, atol=1e-5)
    # empty segments: kernel yields -1e9, XLA's masked max too (both
    # pre-clean) — compare after the common clamp
    np.testing.assert_allclose(
        jnp.where(outs[2] <= -_BIG * 0.5, -_BIG, outs[2]),
        jnp.where(ref["mxmn"] <= -_BIG * 0.5, -_BIG, ref["mxmn"]),
        rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(outs[3], ref["cnt"], rtol=1e-6, atol=1e-6)


@pytest.mark.parametrize("quantize", [False, True],
                         ids=["distinct", "with-ties"])
def test_scatter_gradients_match_composed(quantize):
    """d(sum)/d(sq)/d(max)/d(min) vs the composed twin, including the
    even tie split jax.ops.segment_max's VJP applies."""
    b = _batch(seed=2)
    data = _edge_data(b, seed=3, quantize=quantize)
    ids, mask = jnp.asarray(b.receivers), jnp.asarray(b.edge_mask)
    n = b.x.shape[0]
    f = data.shape[1]

    def loss_fused(d):
        s, q, mxmn, cnt = segment_poly_dense(d, ids, n, ALL_MOMENTS,
                                             valid=mask)
        mx = jnp.where(mxmn[:, :f] <= -_BIG * 0.5, 0.0, mxmn[:, :f])
        mn = jnp.where(mxmn[:, f:] <= -_BIG * 0.5, 0.0, -mxmn[:, f:])
        return (jnp.sum(s ** 2) + 0.5 * jnp.sum(q ** 2)
                + jnp.sum(mx ** 2) + jnp.sum(mn ** 3) + jnp.sum(cnt))

    def loss_ref(d):
        r = _refs(d, ids, mask, n)
        mm = jnp.where(r["mxmn"] <= -_BIG * 0.5, 0.0, r["mxmn"])
        return (jnp.sum(r["sum"] ** 2) + 0.5 * jnp.sum(r["sq"] ** 2)
                + jnp.sum(mm[:, :f] ** 2) + jnp.sum((-mm[:, f:]) ** 3)
                + jnp.sum(r["cnt"]))

    g1 = jax.grad(loss_fused)(data)
    g2 = jax.grad(loss_ref)(data)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-4, atol=1e-4)
    # masked edges must carry EXACTLY zero gradient
    m = np.asarray(b.edge_mask)
    assert np.all(np.asarray(g1)[m == 0] == 0.0)


def test_gather_forward_and_gradients():
    """Gather mode (messages formed in-VMEM): all moments of x[senders]
    over real edges, fwd + dx vs the materialized composed twin."""
    b = _batch(seed=7)
    rng = np.random.RandomState(8)
    n = b.x.shape[0]
    f = 40
    x = jnp.asarray(rng.rand(n, f), jnp.float32)
    s, r = jnp.asarray(b.senders), jnp.asarray(b.receivers)
    mask = jnp.asarray(b.edge_mask)
    perm = _sender_perm(b)

    outs = gather_poly_segment(x, s, r, perm, ALL_MOMENTS, mask=mask)
    ref = _refs(x[s], r, mask, n)
    np.testing.assert_allclose(outs[0], ref["sum"], rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(outs[1], ref["sq"], rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        jnp.where(outs[2] <= -_BIG * 0.5, -_BIG, outs[2]),
        jnp.where(ref["mxmn"] <= -_BIG * 0.5, -_BIG, ref["mxmn"]),
        rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(outs[3], ref["cnt"], rtol=1e-6, atol=1e-6)

    def loss_fused(x_):
        su, q, mxmn, cnt = gather_poly_segment(x_, s, r, perm, ALL_MOMENTS,
                                               mask=mask)
        mx = jnp.where(mxmn[:, :f] <= -_BIG * 0.5, 0.0, mxmn[:, :f])
        mn = jnp.where(mxmn[:, f:] <= -_BIG * 0.5, 0.0, -mxmn[:, f:])
        return (jnp.sum(su ** 2) + 0.5 * jnp.sum(q ** 2)
                + jnp.sum(mx ** 2) + jnp.sum(mn ** 3))

    def loss_ref(x_):
        rr = _refs(x_[s], r, mask, n)
        mm = jnp.where(rr["mxmn"] <= -_BIG * 0.5, 0.0, rr["mxmn"])
        return (jnp.sum(rr["sum"] ** 2) + 0.5 * jnp.sum(rr["sq"] ** 2)
                + jnp.sum(mm[:, :f] ** 2) + jnp.sum((-mm[:, f:]) ** 3))

    g1 = jax.grad(loss_fused)(x)
    g2 = jax.grad(loss_ref)(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-4, atol=1e-4)


def test_gather_sum_cnt_only():
    """The SAGE/MFC moment set (sum + cnt): forward and the one-pass
    fused backward (no [E, F] intermediate) vs the composed twin."""
    b = _batch(seed=9)
    rng = np.random.RandomState(10)
    n = b.x.shape[0]
    x = jnp.asarray(rng.rand(n, 32), jnp.float32)
    s, r = jnp.asarray(b.senders), jnp.asarray(b.receivers)
    mask = jnp.asarray(b.edge_mask)
    perm = _sender_perm(b)

    su, cnt = gather_poly_segment(x, s, r, perm, ("sum", "cnt"), mask=mask)
    np.testing.assert_allclose(
        su, jax.ops.segment_sum(x[s] * mask[:, None], r, num_segments=n),
        rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(
        cnt, jax.ops.segment_sum(mask, r, num_segments=n),
        rtol=1e-6, atol=1e-6)
    # the neighbor-MEAN composition SAGE uses (max(cnt,1) divide)
    mean = su / jnp.maximum(cnt, 1.0)[:, None]
    np.testing.assert_allclose(
        mean, np.asarray(segment.gather_segment_mean(x, b)),
        rtol=1e-5, atol=1e-5)

    g1 = jax.grad(lambda x_: jnp.sum(gather_poly_segment(
        x_, s, r, perm, ("sum", "cnt"), mask=mask)[0] ** 2))(x)
    g2 = jax.grad(lambda x_: jnp.sum(jax.ops.segment_sum(
        x_[s] * mask[:, None], r, num_segments=n) ** 2))(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-4, atol=1e-4)


def test_all_masked_segment_yields_zero_moments():
    """A node with NO real in-edges (every slot masked) must read 0 for
    every cleaned moment — the segment_mean/max/min empty conventions."""
    b = _batch(seed=11)
    e = b.senders.shape[0]
    data = _edge_data(b, seed=12) + 5.0   # strictly positive: a leaked
    ids = jnp.asarray(b.receivers)        # masked max would be visibly > 0
    n = b.x.shape[0]
    mask = jnp.zeros((e,), jnp.float32)   # EVERYTHING masked
    s, q, mxmn, cnt = segment_poly_dense(data, ids, n, ALL_MOMENTS,
                                         valid=mask)
    assert np.all(np.asarray(s) == 0.0)
    assert np.all(np.asarray(q) == 0.0)
    assert np.all(np.asarray(cnt) == 0.0)
    f = data.shape[1]
    mx = jnp.where(mxmn[:, :f] <= -_BIG * 0.5, 0.0, mxmn[:, :f])
    mn = jnp.where(mxmn[:, f:] <= -_BIG * 0.5, 0.0, -mxmn[:, f:])
    assert np.all(np.asarray(mx) == 0.0)
    assert np.all(np.asarray(mn) == 0.0)


# ---------------------------------------------------------------------------
# 3. EGCL interaction-block spec (ops/egcl_mp.py on the builder)
# ---------------------------------------------------------------------------

F, H = 16, 24  # distinct feature/hidden widths catch f/h transpositions


def _egcl_batch(n_graphs=6, nodes=9, seed=0, isolate=False):
    rng = np.random.RandomState(seed)
    samples = []
    for i in range(n_graphs):
        pos = rng.rand(nodes, 3).astype(np.float32) * 2.2
        if isolate and i == 0:
            # empty segments: park two nodes far outside every cutoff so
            # they have NO incident edges (their agg/psum rows must read 0)
            pos[-2:] += 50.0
        samples.append(GraphSample(
            x=rng.rand(nodes, 2).astype(np.float32), pos=pos,
            edge_index=radius_graph(pos, 1.4, 8),
            graph_y=rng.rand(1).astype(np.float32)))
    pad = PadSpec.for_batch(n_graphs, nodes,
                            max(s.num_edges for s in samples))
    prev = os.environ.get("HYDRAGNN_AGGR_BACKEND")
    os.environ["HYDRAGNN_AGGR_BACKEND"] = "fused"
    try:
        return collate(samples, pad, [HeadSpec("e", "graph", 1)])
    finally:
        if prev is None:
            os.environ.pop("HYDRAGNN_AGGR_BACKEND", None)
        else:
            os.environ["HYDRAGNN_AGGR_BACKEND"] = prev


def _egcl_inputs(g, seed=1, edge_attr_dim=0):
    """Random op inputs; geo is [diff(3), radial(1), edge_attr(A)] with
    |diff| < 1 like the real normalized difference."""
    rng = np.random.RandomState(seed)
    n = g.x.shape[0]
    e = g.senders.shape[0]
    x = jnp.asarray(rng.randn(n, F), jnp.float32)
    gd = 4 + edge_attr_dim
    geo = jnp.asarray(rng.rand(e, gd) * 0.8, jnp.float32)
    w0 = jnp.asarray(rng.randn(2 * F + 1 + edge_attr_dim, H) * 0.3,
                     jnp.float32)
    b0 = jnp.asarray(rng.randn(H) * 0.1, jnp.float32)
    w1 = jnp.asarray(rng.randn(H, H) * 0.3, jnp.float32)
    b1 = jnp.asarray(rng.randn(H) * 0.1, jnp.float32)
    wc0 = jnp.asarray(rng.randn(H, H) * 0.3, jnp.float32)
    bc0 = jnp.asarray(rng.randn(H) * 0.1, jnp.float32)
    wc1 = jnp.asarray(rng.randn(H, 1) * 0.5, jnp.float32)
    return x, geo, w0, b0, w1, b1, wc0, bc0, wc1


def _egcl_composed(x, geo, mask, w0, b0, w1, b1, wc0, bc0, wc1,
                   senders, receivers, n, equivariant):
    """The composed-path math (models/egnn.py fallback route), on raw
    weights."""
    diff, feat = geo[:, :3], geo[:, 3:]
    m = jnp.concatenate([x[senders], x[receivers], feat], axis=-1)
    m = jax.nn.relu(m @ w0 + b0)
    m = jax.nn.relu(m @ w1 + b1)
    m = m * mask[:, None]
    agg = jax.ops.segment_sum(m, senders, num_segments=n)
    if not equivariant:
        return agg, None
    c = jax.nn.relu(m @ wc0 + bc0)
    c = jnp.tanh(c @ wc1)
    trans = jnp.clip(diff * c, -100.0, 100.0) * mask[:, None]
    psum = jax.ops.segment_sum(trans, senders, num_segments=n)
    return agg, psum


def _run_egcl_fused(g, args, equivariant):
    x, geo = args[0], args[1]
    em = jnp.asarray(g.edge_mask).astype(jnp.int32)
    perm = jnp.asarray(g.extras["edge_perm_sender"])
    if equivariant:
        return egcl_block(True, x, geo, em, *args[2:],
                          g.senders, g.receivers, perm)
    return egcl_block(False, x, geo, em, *args[2:6], None, None, None,
                      g.senders, g.receivers, perm)


def test_egcl_forward_matches_composed():
    g = _egcl_batch()
    args = _egcl_inputs(g)
    mask = jnp.asarray(g.edge_mask)
    agg, psum = _run_egcl_fused(g, args, True)
    ref_agg, ref_psum = _egcl_composed(args[0], args[1], mask, *args[2:],
                                       g.senders, g.receivers,
                                       args[0].shape[0], True)
    np.testing.assert_allclose(np.asarray(agg), np.asarray(ref_agg),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(psum[:, :3]),
                               np.asarray(ref_psum), rtol=1e-5, atol=1e-5)


def test_egcl_forward_non_equivariant():
    """Last-layer EGCL: no coordinate branch, message sum only."""
    g = _egcl_batch(seed=2)
    args = _egcl_inputs(g, seed=3)
    mask = jnp.asarray(g.edge_mask)
    agg, psum = _run_egcl_fused(g, args, False)
    assert psum is None
    ref_agg, _ = _egcl_composed(args[0], args[1], mask, *args[2:],
                                g.senders, g.receivers, args[0].shape[0],
                                False)
    np.testing.assert_allclose(np.asarray(agg), np.asarray(ref_agg),
                               rtol=1e-5, atol=1e-5)


def test_egcl_forward_empty_segments():
    """Nodes with no incident edges (isolated + padding slots) read
    exactly zero in both outputs."""
    g = _egcl_batch(seed=4, isolate=True)
    args = _egcl_inputs(g, seed=5)
    mask = jnp.asarray(g.edge_mask)
    agg, psum = _run_egcl_fused(g, args, True)
    ref_agg, ref_psum = _egcl_composed(args[0], args[1], mask, *args[2:],
                                       g.senders, g.receivers,
                                       args[0].shape[0], True)
    np.testing.assert_allclose(np.asarray(agg), np.asarray(ref_agg),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(psum[:, :3]),
                               np.asarray(ref_psum), rtol=1e-5, atol=1e-5)
    # the isolated nodes really have no edges (the scenario is live)
    deg = np.zeros(args[0].shape[0])
    np.add.at(deg, np.asarray(g.senders)[np.asarray(mask) > 0], 1.0)
    assert (deg == 0).any()
    assert np.all(np.asarray(agg)[deg == 0] == 0.0)


def _egcl_grad_parity(g, seed, equivariant, edge_attr_dim=0,
                      rtol=3e-4, atol=3e-4):
    args = _egcl_inputs(g, seed=seed, edge_attr_dim=edge_attr_dim)
    mask = jnp.asarray(g.edge_mask)
    n = args[0].shape[0]
    rng = np.random.RandomState(seed + 70)
    wa = jnp.asarray(rng.randn(n, H), jnp.float32)
    wp = jnp.asarray(rng.randn(n, 3), jnp.float32)
    nargs = len(args) if equivariant else 7

    def loss_fused(a):
        agg, psum = _run_egcl_fused(g, a, equivariant)
        out = jnp.sum(agg * wa)
        if equivariant:
            out = out + jnp.sum(psum[:, :3] * wp)
        return out

    def loss_ref(a):
        full = tuple(a) + tuple(args[len(a):])
        agg, psum = _egcl_composed(full[0], full[1], mask, *full[2:],
                                   g.senders, g.receivers, n, equivariant)
        out = jnp.sum(agg * wa)
        if equivariant:
            out = out + jnp.sum(psum * wp)
        return out

    gf = jax.grad(loss_fused)(args[:nargs])
    gr = jax.grad(loss_ref)(args[:nargs])
    emask = np.asarray(g.edge_mask)
    names = ("x", "geo", "w0", "b0", "w1", "b1", "wc0", "bc0", "wc1")
    for name, a, b in zip(names, gf, gr):
        a, b = np.asarray(a), np.asarray(b)
        if name == "geo":
            # contract: masked edges get EXACTLY zero dgeo (their blocks
            # are schedule-skipped; uninitialized rows are where-selected)
            assert np.all(a[emask == 0] == 0.0)
            a, b = a[emask == 1], b[emask == 1]
        np.testing.assert_allclose(a, b, rtol=rtol, atol=atol,
                                   err_msg=name)


def test_egcl_gradients_match_composed():
    _egcl_grad_parity(_egcl_batch(seed=3), seed=6, equivariant=True)


def test_egcl_gradients_non_equivariant():
    _egcl_grad_parity(_egcl_batch(seed=7), seed=8, equivariant=False)


def test_egcl_gradients_with_edge_attr():
    """edge_attr lanes ride the geo stream; their grads must chain too."""
    _egcl_grad_parity(_egcl_batch(seed=9), seed=10, equivariant=True,
                      edge_attr_dim=5)


def test_egcl_model_level_fused_equals_composed(monkeypatch):
    """EGNN with the fused block forced on vs off: same params (the
    DenseParams tree matches the composed path's), same forward, same
    param grads — through BOTH the message and coordinate branches (two
    conv layers: the first is equivariant, so updated positions feed the
    second layer's geometry)."""
    g = _egcl_batch(n_graphs=4, seed=5)  # fewer edge blocks: interpret mode
    cfg = ModelConfig(
        model_type="EGNN", input_dim=2, hidden_dim=F, output_dim=(1,),
        output_type=("graph",), graph_head=GraphHeadCfg(1, 8, 1, (8,)),
        node_head=None, task_weights=(1.0,), num_conv_layers=2,
        equivariance=True, radius=1.4, max_neighbours=8)
    model = create_model(cfg)
    monkeypatch.setenv("HYDRAGNN_EGCL_FUSED", "1")
    variables = model.init({"params": jax.random.PRNGKey(0)}, g,
                           train=False)

    def loss(params, fused):
        monkeypatch.setenv("HYDRAGNN_EGCL_FUSED", "1" if fused else "0")
        out = model.apply({"params": params}, g, train=False)
        return sum(jnp.sum(o * o) for o in out)

    lf = loss(variables["params"], True)
    lg = loss(variables["params"], False)
    np.testing.assert_allclose(float(lf), float(lg), rtol=2e-5)

    gf = jax.grad(lambda p: loss(p, True))(variables["params"])
    gp = jax.grad(lambda p: loss(p, False))(variables["params"])
    flat_f = jax.tree_util.tree_leaves_with_path(gf)
    flat_p = dict(jax.tree_util.tree_leaves_with_path(gp))
    assert flat_f  # same tree structure both ways
    for path, leaf in flat_f:
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat_p[path]), rtol=5e-4,
            atol=5e-4, err_msg=str(path))


def test_egcl_pipeline_gate_defaults(monkeypatch):
    from hydragnn_tpu.models.egnn import _egcl_pipeline_enabled

    # judge the defaults with the env override ABSENT — a developer's
    # ambient HYDRAGNN_EGCL_FUSED would flip the first assert
    monkeypatch.delenv("HYDRAGNN_EGCL_FUSED", raising=False)
    assert _egcl_pipeline_enabled(64, 64, 4)     # mainline: default ON
    assert not _egcl_pipeline_enabled(256, 64, 4)   # features > tile
    assert not _egcl_pipeline_enabled(64, 256, 4)   # hidden > tile
    assert not _egcl_pipeline_enabled(64, 64, 200)  # geo payload > lanes
    monkeypatch.setenv("HYDRAGNN_EGCL_FUSED", "0")
    assert not _egcl_pipeline_enabled(64, 64, 4)    # forced off
    monkeypatch.setenv("HYDRAGNN_EGCL_FUSED", "1")
    assert _egcl_pipeline_enabled(128, 128, 4)      # forced on


def test_egcl_bf16_forward_within_tolerance():
    """bf16 node features ride bf16 windows in VMEM; result must stay
    within bf16 tolerance of the f32 composed path."""
    g = _egcl_batch(seed=6)
    args = _egcl_inputs(g, seed=12)
    mask = jnp.asarray(g.edge_mask)
    bf_args = (args[0].astype(jnp.bfloat16),) + args[1:]
    agg, psum = _run_egcl_fused(g, bf_args, True)
    assert agg.dtype == jnp.bfloat16
    ref_agg, ref_psum = _egcl_composed(args[0], args[1], mask, *args[2:],
                                       g.senders, g.receivers,
                                       args[0].shape[0], True)
    for out, ref in ((agg, ref_agg), (psum[:, :3], ref_psum)):
        ref = np.asarray(ref, np.float32)
        scale = np.abs(ref).max() + 1e-6
        err = np.abs(np.asarray(out, np.float32) - ref).max() / scale
        assert err < 0.03, err


def test_egcl_bf16_gradients_within_tolerance():
    """bf16 operands through the fused backward (weight grads included)
    stay within bf16 drift of the f32 composed reference."""
    g = _egcl_batch(seed=13)
    args = _egcl_inputs(g, seed=14)
    mask = jnp.asarray(g.edge_mask)
    n = args[0].shape[0]
    rng = np.random.RandomState(15)
    wa = jnp.asarray(rng.randn(n, H), jnp.float32)

    def loss_fused(a):
        bf = (a[0].astype(jnp.bfloat16),) + tuple(a[1:])
        agg, psum = _run_egcl_fused(g, bf, True)
        return (jnp.sum(agg.astype(jnp.float32) * wa)
                + jnp.sum(psum[:, :3]))

    def loss_ref(a):
        agg, psum = _egcl_composed(a[0], a[1], mask, *a[2:],
                                   g.senders, g.receivers, n, True)
        return jnp.sum(agg * wa) + jnp.sum(psum)

    gf = jax.grad(loss_fused)(args)
    gr = jax.grad(loss_ref)(args)
    emask = np.asarray(g.edge_mask).astype(bool)
    names = ("x", "geo", "w0", "b0", "w1", "b1", "wc0", "bc0", "wc1")
    for name, a, b in zip(names, gf, gr):
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        if name == "geo":
            a, b = a[emask], b[emask]
        scale = np.abs(b).max() + 1e-6
        err = np.abs(a - b).max() / scale
        # deeper chain than scf's two matmuls (edge MLP + coord gate +
        # tanh, 4 bf16 matmul layers each way) — drift bound scales with
        # depth; observed ~0.067 max on x grads.  geo's diff lanes carry
        # the gate value c itself (ddiff = c * dpsum), whose relative
        # error is the whole chain's accumulated drift: widest bound.
        assert err < (0.20 if name == "geo" else 0.10), (name, err)


# ---------------------------------------------------------------------------
# 4. CGCNN gated-sum spec (ops/cgcnn_mp.py on the builder)
# ---------------------------------------------------------------------------


def _cgcnn_ref(x, ea, mask, kf, bf, ks, bs, senders, receivers, n):
    """The composed-path gated sum (models/cgcnn.py fallback route)."""
    parts = [x[receivers], x[senders]]
    if ea is not None:
        parts.append(ea)
    z = jnp.concatenate(parts, axis=-1)
    gate = jax.nn.sigmoid(z @ kf + bf)
    core = jax.nn.softplus(z @ ks + bs)
    return jax.ops.segment_sum(gate * core * mask[:, None], receivers,
                               num_segments=n)


def test_cgcnn_gated_block_parity_with_edge_attr(monkeypatch):
    """Forward + grads (x, edge_attr, both kernel/bias pairs) vs the
    composed concat path, incl. the exactly-zero-grad contract on
    masked edges."""
    from hydragnn_tpu.ops.cgcnn_mp import cgcnn_gated_block

    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", "fused")
    b = _batch(seed=21)
    rng = np.random.RandomState(22)
    n = b.x.shape[0]
    e = b.senders.shape[0]
    f, a, d = 24, 5, 16  # distinct in/attr/out widths catch transpositions
    x = jnp.asarray(rng.randn(n, f) * 0.5, jnp.float32)
    ea = jnp.asarray(rng.randn(e, a) * 0.5, jnp.float32)
    kf = jnp.asarray(rng.randn(2 * f + a, d) * 0.3, jnp.float32)
    bf = jnp.asarray(rng.randn(d) * 0.1, jnp.float32)
    ks = jnp.asarray(rng.randn(2 * f + a, d) * 0.3, jnp.float32)
    bs = jnp.asarray(rng.randn(d) * 0.1, jnp.float32)
    em = jnp.asarray(b.edge_mask).astype(jnp.int32)
    mask = jnp.asarray(b.edge_mask)
    perm = jnp.asarray(b.extras["edge_perm_sender"])
    s, r = jnp.asarray(b.senders), jnp.asarray(b.receivers)
    wa = jnp.asarray(rng.randn(n, d), jnp.float32)

    out = cgcnn_gated_block(x, ea, em, kf, bf, ks, bs, s, r, perm)
    ref = _cgcnn_ref(x, ea, mask, kf, bf, ks, bs, s, r, n)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)

    gf = jax.grad(lambda x_, ea_, kf_, bf_, ks_, bs_: jnp.sum(
        cgcnn_gated_block(x_, ea_, em, kf_, bf_, ks_, bs_, s, r, perm)
        * wa), argnums=(0, 1, 2, 3, 4, 5))(x, ea, kf, bf, ks, bs)
    gr = jax.grad(lambda x_, ea_, kf_, bf_, ks_, bs_: jnp.sum(
        _cgcnn_ref(x_, ea_, mask, kf_, bf_, ks_, bs_, s, r, n) * wa),
        argnums=(0, 1, 2, 3, 4, 5))(x, ea, kf, bf, ks, bs)
    names = ("x", "edge_attr", "kf", "bf", "ks", "bs")
    emask = np.asarray(b.edge_mask)
    for name, gfa, gra in zip(names, gf, gr):
        gfa, gra = np.asarray(gfa), np.asarray(gra)
        if name == "edge_attr":
            assert np.all(gfa[emask == 0] == 0.0)
            gfa, gra = gfa[emask == 1], gra[emask == 1]
        np.testing.assert_allclose(gfa, gra, rtol=3e-4, atol=3e-4,
                                   err_msg=name)


def test_cgcnn_gated_block_no_edge_attr_bf16(monkeypatch):
    """edge_attr=None (zero-width geo payload, bias lane only) and bf16
    inputs: output dtype follows x, drift within bf16 tolerance."""
    from hydragnn_tpu.ops.cgcnn_mp import cgcnn_gated_block

    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", "fused")
    b = _batch(seed=23)
    rng = np.random.RandomState(24)
    n = b.x.shape[0]
    f = 16
    x = jnp.asarray(rng.randn(n, f) * 0.5, jnp.float32)
    kf = jnp.asarray(rng.randn(2 * f, f) * 0.3, jnp.float32)
    bf = jnp.asarray(rng.randn(f) * 0.1, jnp.float32)
    ks = jnp.asarray(rng.randn(2 * f, f) * 0.3, jnp.float32)
    bs = jnp.asarray(rng.randn(f) * 0.1, jnp.float32)
    em = jnp.asarray(b.edge_mask).astype(jnp.int32)
    mask = jnp.asarray(b.edge_mask)
    perm = jnp.asarray(b.extras["edge_perm_sender"])
    s, r = jnp.asarray(b.senders), jnp.asarray(b.receivers)

    out = cgcnn_gated_block(x, None, em, kf, bf, ks, bs, s, r, perm)
    ref = _cgcnn_ref(x, None, mask, kf, bf, ks, bs, s, r, n)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=1e-4, atol=1e-4)

    out_bf = cgcnn_gated_block(x.astype(jnp.bfloat16), None, em,
                               kf, bf, ks, bs, s, r, perm)
    assert out_bf.dtype == jnp.bfloat16
    refn = np.asarray(ref, np.float32)
    scale = np.abs(refn).max() + 1e-6
    err = np.abs(np.asarray(out_bf, np.float32) - refn).max() / scale
    assert err < 0.03, err


# ---------------------------------------------------------------------------
# 5. DimeNet triplet paths
# ---------------------------------------------------------------------------


def test_dimenet_fused_triplet_parity(monkeypatch):
    """The edge-space fused triplet interaction (tri_window > 0, W-window
    gather_mul_segment_sum) must match the composed gather+scatter path in
    forward AND param gradients on a real collated DimeNet batch."""
    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", "fused")
    monkeypatch.setenv("HYDRAGNN_DIMENET_FUSED_TRI", "1")
    from hydragnn_tpu.models.dimenet import add_dimenet_extras, count_triplets

    rng = np.random.RandomState(0)
    samples = []
    for _ in range(5):
        pos = rng.rand(8, 3).astype(np.float32) * 2.0
        samples.append(GraphSample(
            x=rng.randint(0, 4, (8, 1)).astype(np.float32), pos=pos,
            edge_index=radius_graph(pos, 1.5, 8),
            graph_y=rng.rand(1).astype(np.float32)))
    pad = PadSpec.for_batch(5, 8, max(s.num_edges for s in samples))
    batch = collate(samples, pad, [HeadSpec("e", "graph", 1)])
    real = np.asarray(batch.edge_mask) > 0
    ei_real = np.stack([np.asarray(batch.senders)[real],
                        np.asarray(batch.receivers)[real]])
    t = count_triplets(ei_real, batch.x.shape[0])
    batch = add_dimenet_extras(batch, max_triplets=t + 8)
    assert "dn_tri_window" in batch.extras, "span must fit the window here"

    cfg = ModelConfig(
        model_type="DimeNet", input_dim=1, hidden_dim=8, output_dim=(1,),
        output_type=("graph",), graph_head=GraphHeadCfg(1, 8, 1, (8,)),
        node_head=None, task_weights=(1.0,), num_conv_layers=2,
        num_radial=3, num_spherical=4, basis_emb_size=4, int_emb_size=8,
        out_emb_size=8, envelope_exponent=5, num_before_skip=1,
        num_after_skip=1, radius=1.5)
    model = create_model(cfg)
    params = model.init({"params": jax.random.PRNGKey(0)}, batch,
                        train=False)["params"]

    ex_plain = dict(batch.extras)
    del ex_plain["dn_tri_window"]
    batch_plain = batch.replace(extras=ex_plain)

    def loss(p, b):
        out = model.apply({"params": p}, b, train=False)
        return sum(jnp.sum(o ** 2) for o in out)

    lf, gf = jax.value_and_grad(loss)(params, batch)
    lp, gp = jax.value_and_grad(loss)(params, batch_plain)
    assert abs(float(lf) - float(lp)) < 1e-4 * max(1.0, abs(float(lp)))
    for a, c in zip(jax.tree_util.tree_leaves(gf),
                    jax.tree_util.tree_leaves(gp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=2e-3, atol=2e-3)


def test_dimenet_tri_builder_wide_dims_parity(monkeypatch):
    """int_emb_size > the factored kernel's cap routes the triplet
    interaction onto the builder-backed fused path (ops/dn_tri.py
    dimenet_tri_builder) instead of falling back to the composed
    gather+scatter — forward and param grads must match the composed
    route, and the branch selection itself is asserted."""
    import hydragnn_tpu.models.dimenet as D
    from test_dn_tri import _tables

    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", "fused")
    batch_on = _tables(seed=8)
    assert "dn_tri_ok" in batch_on.extras
    monkeypatch.setenv("HYDRAGNN_DN_TRI_OFF", "1")
    batch_off = _tables(seed=8)
    assert "dn_tri_ok" not in batch_off.extras
    monkeypatch.delenv("HYDRAGNN_DN_TRI_OFF")

    # int_emb_size=96 > 64: the factored-basis kernel rejects, the
    # builder path (caps at 128) activates
    cfg = ModelConfig(
        model_type="DimeNet", input_dim=1, hidden_dim=16, output_dim=(1,),
        output_type=("graph",), graph_head=GraphHeadCfg(1, 8, 1, (8,)),
        node_head=None, task_weights=(1.0,), num_conv_layers=2,
        radius=1.3, max_neighbours=6, envelope_exponent=5,
        num_before_skip=1, num_after_skip=1, num_radial=3,
        num_spherical=7, basis_emb_size=8, int_emb_size=96,
        out_emb_size=16)

    seen = {}
    orig = D.InteractionPPBlock.__call__

    def patched(self, *a, **k):
        seen["kernel"] = self.tri_kernel
        seen["builder"] = self.tri_builder
        return orig(self, *a, **k)

    monkeypatch.setattr(D.InteractionPPBlock, "__call__", patched)

    model = create_model(cfg)
    variables = model.init({"params": jax.random.PRNGKey(0)}, batch_on,
                           train=False)
    assert seen == {"kernel": False, "builder": True}, seen

    def loss(params, batch):
        out = model.apply({"params": params}, batch, train=False)
        return sum(jnp.sum(o * o) for o in out)

    l_on = float(loss(variables["params"], batch_on))
    l_off = float(loss(variables["params"], batch_off))
    np.testing.assert_allclose(l_on, l_off, rtol=2e-5)

    g_on = jax.grad(lambda p: loss(p, batch_on))(variables["params"])
    g_off = jax.grad(lambda p: loss(p, batch_off))(variables["params"])
    flat_on = jax.tree_util.tree_leaves_with_path(g_on)
    flat_off = dict(jax.tree_util.tree_leaves_with_path(g_off))
    assert flat_on
    for path, leaf in flat_on:
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat_off[path]),
            rtol=5e-4, atol=5e-4, err_msg=str(path))


# ---------------------------------------------------------------------------
# 6. gather-mul / dense segment-sum schedule kernels (ops/fused_mp.py)
# ---------------------------------------------------------------------------


def _arrays(b, f=64, seed=1):
    rng = np.random.RandomState(seed)
    n, e = b.x.shape[0], b.senders.shape[0]
    x = jnp.asarray(rng.rand(n, f), jnp.float32)
    w = jnp.asarray(rng.rand(e, f), jnp.float32) * jnp.asarray(
        b.edge_mask)[:, None]
    return x, w, _sender_perm(b)


def _gms_ref(b, x, w):
    return jax.ops.segment_sum(
        x[jnp.asarray(b.senders)] * w, jnp.asarray(b.receivers),
        num_segments=x.shape[0])


def test_fused_forward_exact():
    b = _batch()
    x, w, perm = _arrays(b)
    out = gather_mul_segment_sum(
        x, w, jnp.asarray(b.senders), jnp.asarray(b.receivers), perm)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_gms_ref(b, x, w)),
                               rtol=1e-5, atol=1e-5)


def test_fused_gradients_exact():
    b = _batch(seed=2)
    x, w, perm = _arrays(b, seed=3)
    s, r = jnp.asarray(b.senders), jnp.asarray(b.receivers)

    gx1, gw1 = jax.grad(
        lambda x_, w_: jnp.sum(
            gather_mul_segment_sum(x_, w_, s, r, perm) ** 2),
        argnums=(0, 1))(x, w)
    gx2, gw2 = jax.grad(
        lambda x_, w_: jnp.sum(_gms_ref(b, x_, w_) ** 2),
        argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx1), np.asarray(gx2),
                               rtol=1e-5, atol=1e-5)
    m = np.asarray(b.edge_mask)[:, None]
    np.testing.assert_allclose(np.asarray(gw1) * m, np.asarray(gw2) * m,
                               rtol=1e-5, atol=1e-5)


def test_extreme_degrees_exact():
    """The dense schedule has no degree bound: dense all-to-all graphs
    (degree 15 in a 16-node graph) are processed exactly, fwd and bwd."""
    rng = np.random.RandomState(0)
    samples = []
    for _ in range(24):
        n = 16
        pos = rng.rand(n, 3).astype(np.float32)  # dense: everyone in range
        x = rng.rand(n, 2).astype(np.float32)
        ei = radius_graph(pos, 10.0, 15)
        samples.append(GraphSample(x=x, pos=pos, edge_index=ei,
                                   graph_y=np.ones(1, np.float32), node_y=x))
    pad = PadSpec.for_batch(24, 16, 16 * 15)
    b = collate(samples, pad, [HeadSpec("e", "graph", 1)])
    x, w, perm = _arrays(b)
    s, r = jnp.asarray(b.senders), jnp.asarray(b.receivers)
    out = gather_mul_segment_sum(x, w, s, r, perm)
    np.testing.assert_allclose(np.asarray(out),
                               np.asarray(_gms_ref(b, x, w)),
                               rtol=1e-5, atol=1e-5)
    gx1 = jax.grad(lambda x_: jnp.sum(
        gather_mul_segment_sum(x_, w, s, r, perm) ** 2))(x)
    gx2 = jax.grad(lambda x_: jnp.sum(_gms_ref(b, x_, w) ** 2))(x)
    np.testing.assert_allclose(np.asarray(gx1), np.asarray(gx2),
                               rtol=1e-5, atol=1e-5)


def test_gather_segment_sum_wless_exact():
    """The w-less variant (GIN/MFC neighbor sum) and its gradient."""
    from hydragnn_tpu.ops.fused_mp import gather_segment_sum

    b = _batch(seed=7)
    x, _, perm = _arrays(b, seed=8)
    s, r = jnp.asarray(b.senders), jnp.asarray(b.receivers)
    mask = jnp.asarray(b.edge_mask)

    out = gather_segment_sum(x, s, r, perm, mask)
    want = jax.ops.segment_sum(
        x[s] * mask[:, None], r, num_segments=x.shape[0])
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-5)

    g1 = jax.grad(lambda x_: jnp.sum(
        gather_segment_sum(x_, s, r, perm, mask) ** 2))(x)
    g2 = jax.grad(lambda x_: jnp.sum(jax.ops.segment_sum(
        x_[s] * mask[:, None], r, num_segments=x.shape[0]) ** 2))(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-5, atol=1e-5)


def test_segment_sum_dense_exact():
    """Scatter-only dense-schedule kernel vs jax.ops.segment_sum, fwd+bwd,
    over both sorted id streams the models use (receivers, node_gid)."""
    from hydragnn_tpu.ops.fused_mp import segment_sum_dense

    b = _batch(seed=11)
    rng = np.random.RandomState(12)
    e = b.senders.shape[0]
    data = jnp.asarray(rng.rand(e, 48), jnp.float32) * jnp.asarray(
        b.edge_mask)[:, None]
    r = jnp.asarray(b.receivers)
    n = b.x.shape[0]
    np.testing.assert_allclose(
        np.asarray(segment_sum_dense(data, r, n)),
        np.asarray(jax.ops.segment_sum(data, r, num_segments=n)),
        rtol=1e-5, atol=1e-5)
    g1 = jax.grad(lambda d: jnp.sum(segment_sum_dense(d, r, n) ** 2))(data)
    g2 = jax.grad(lambda d: jnp.sum(
        jax.ops.segment_sum(d, r, num_segments=n) ** 2))(data)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-5, atol=1e-5)

    nd = jnp.asarray(rng.rand(n, 32), jnp.float32)
    gid = jnp.asarray(b.node_gid)
    ng = b.graph_mask.shape[0]
    np.testing.assert_allclose(
        np.asarray(segment_sum_dense(nd, gid, ng)),
        np.asarray(jax.ops.segment_sum(nd, gid, num_segments=ng)),
        rtol=1e-5, atol=1e-5)


def test_dense_bwd_gathers_exact(monkeypatch):
    """gather_sender / gather_receiver_sorted: forward identical to plain
    gathers, backward (dense-scatter path) identical to XLA's."""
    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", "fused")
    b = _batch(seed=13)
    rng = np.random.RandomState(14)
    x = jnp.asarray(rng.rand(b.x.shape[0], 32), jnp.float32)

    for fn, idx in ((segment.gather_sender, b.senders),
                    (segment.gather_receiver_sorted, b.receivers)):
        np.testing.assert_array_equal(
            np.asarray(fn(x, b)), np.asarray(x[jnp.asarray(idx)]))
        g1 = jax.grad(lambda x_: jnp.sum(fn(x_, b) ** 2))(x)
        g2 = jax.grad(lambda x_: jnp.sum(x_[jnp.asarray(idx)] ** 2))(x)
        # f32 accumulation order differs between the onehot-matmul scatter
        # and XLA's scatter-add; values here reach ~1e4
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=1e-4, atol=1e-5)


# ---------------------------------------------------------------------------
# 7. collate invariants + trace-time dispatch tally
# ---------------------------------------------------------------------------


def test_collate_attaches_perm_under_fused_backend(monkeypatch):
    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", "fused")
    b = _batch()
    assert "edge_perm_sender" in b.extras
    perm = np.asarray(b.extras["edge_perm_sender"])
    s = np.asarray(b.senders)
    assert (np.diff(s[perm]) >= 0).all()
    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", "scatter")
    b2 = _batch()
    assert "edge_perm_sender" not in (b2.extras or {})


def test_collate_skips_perm_when_invariants_broken(monkeypatch):
    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", "fused")
    rng = np.random.RandomState(0)

    # graph larger than the kernel's node block -> no perm
    n = 200
    pos = rng.rand(n, 3).astype(np.float32) * 6.0
    x = rng.rand(n, 2).astype(np.float32)
    ei = radius_graph(pos, 1.4, 10)
    big = GraphSample(x=x, pos=pos, edge_index=ei,
                      graph_y=np.ones(1, np.float32), node_y=x)
    pad = PadSpec.for_batch(1, n, n * 10)
    b = collate([big], pad, [HeadSpec("e", "graph", 1)])
    assert "edge_perm_sender" not in (b.extras or {})

    # receiver-unsorted stored edge list (external pipeline) -> no perm
    n2 = 8
    pos2 = rng.rand(n2, 3).astype(np.float32)
    x2 = rng.rand(n2, 2).astype(np.float32)
    ei2 = np.asarray([[1, 0, 3], [5, 2, 0]], np.int32)  # recv not sorted
    small = GraphSample(x=x2, pos=pos2, edge_index=ei2,
                        graph_y=np.ones(1, np.float32), node_y=x2)
    pad2 = PadSpec.for_batch(1, n2, 8)
    b2 = collate([small], pad2, [HeadSpec("e", "graph", 1)])
    assert "edge_perm_sender" not in (b2.extras or {})


def test_dispatcher_fused_matches_fallback(monkeypatch):
    """poly_scatter_segment / poly_gather_segment: the fused dict (marker
    present) must equal the composed dict (marker stripped), including
    the mx/mn empty-segment zero-clean and cnt == degree."""
    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", "fused")
    b = _batch(seed=13)
    assert "edge_perm_sender" in b.extras
    ex = dict(b.extras)
    del ex["edge_perm_sender"]
    b_plain = b.replace(extras=ex)

    data = _edge_data(b, seed=14)
    moments = ("sum", "sq", "mx", "mn", "cnt")
    rf = segment.poly_scatter_segment(data, b, moments)
    rp = segment.poly_scatter_segment(data, b_plain, moments)
    for k in moments:
        np.testing.assert_allclose(np.asarray(rf[k]), np.asarray(rp[k]),
                                   rtol=1e-5, atol=1e-5, err_msg=k)

    rng = np.random.RandomState(15)
    x = jnp.asarray(rng.rand(b.x.shape[0], 24), jnp.float32)
    gf = segment.poly_gather_segment(x, b, moments)
    gp = segment.poly_gather_segment(x, b_plain, moments)
    for k in moments:
        np.testing.assert_allclose(np.asarray(gf[k]), np.asarray(gp[k]),
                                   rtol=1e-5, atol=1e-5, err_msg=k)


def test_dispatch_tally_counts_fused_and_fallback(monkeypatch):
    """The trace-time dispatch tally: a marker-carrying batch counts
    :fused, a marker-less one :scatter, and the width gate falls back
    (the silent-fast-path-loss signal the telemetry manifest surfaces)."""
    from hydragnn_tpu.ops.poly_mp import POLY_MAX_F_MXMN
    from hydragnn_tpu.telemetry import pipeline

    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", "fused")
    b = _batch(seed=16)
    data = _edge_data(b, seed=17, f=16)

    base = pipeline.dispatch_snapshot()
    segment.poly_scatter_segment(data, b, ("sum", "mx"))
    d1 = pipeline.dispatch_snapshot()
    assert d1.get("poly_scatter:fused", 0) \
        == base.get("poly_scatter:fused", 0) + 1

    ex = dict(b.extras)
    del ex["edge_perm_sender"]
    segment.poly_scatter_segment(data, b.replace(extras=ex), ("sum", "mx"))
    d2 = pipeline.dispatch_snapshot()
    assert d2.get("poly_scatter:scatter", 0) \
        == d1.get("poly_scatter:scatter", 0) + 1

    # width gate: F above the mxmn cap must take the composed path even
    # with the marker present — and still be numerically right
    wide = jnp.asarray(
        np.random.RandomState(18).rand(b.senders.shape[0],
                                       POLY_MAX_F_MXMN + 1), jnp.float32)
    out = segment.poly_scatter_segment(wide, b, ("sum", "mx"))
    d3 = pipeline.dispatch_snapshot()
    assert d3.get("poly_scatter:scatter", 0) \
        == d2.get("poly_scatter:scatter", 0) + 1
    np.testing.assert_allclose(
        np.asarray(out["sum"]),
        np.asarray(jax.ops.segment_sum(
            wide * jnp.asarray(b.edge_mask)[:, None],
            jnp.asarray(b.receivers), num_segments=b.x.shape[0])),
        rtol=1e-5, atol=1e-5)

    assert pipeline.dispatch_summary(
        {"poly_scatter:fused": 2}) == "fused"
    assert pipeline.dispatch_summary(
        {"a:fused": 1, "b:scatter": 2}) == "mixed(fused=1,scatter=2)"


def test_dispatch_tally_counts_egcl(monkeypatch):
    """The egcl dispatch site tallies fused vs scatter — that tally is
    what makes EGNN visible to bench's per-arch aggr_backend column —
    and a requested-but-denied fused path records a unified
    fused_fallback event carrying {arch, reason}."""
    from hydragnn_tpu.telemetry import pipeline as tp

    g = _egcl_batch(seed=11)
    cfg = ModelConfig(
        model_type="EGNN", input_dim=2, hidden_dim=F, output_dim=(1,),
        output_type=("graph",), graph_head=GraphHeadCfg(1, 8, 1, (8,)),
        node_head=None, task_weights=(1.0,), num_conv_layers=2,
        equivariance=True, radius=1.4, max_neighbours=8)
    model = create_model(cfg)
    monkeypatch.setenv("HYDRAGNN_EGCL_FUSED", "1")
    before = tp.dispatch_snapshot()
    variables = model.init({"params": jax.random.PRNGKey(0)}, g,
                           train=False)
    model.apply({"params": variables["params"]}, g, train=False)
    delta = tp.dispatch_delta(before, tp.dispatch_snapshot())
    assert delta.get("egcl:fused", 0) > 0
    monkeypatch.setenv("HYDRAGNN_EGCL_FUSED", "0")
    before = tp.dispatch_snapshot()
    model.apply({"params": variables["params"]}, g, train=False)
    delta = tp.dispatch_delta(before, tp.dispatch_snapshot())
    assert delta.get("egcl:scatter", 0) > 0
    # forcing fused requested-but-denied records the fallback reason on
    # the unified "fused" channel, tagged with the arch
    tp.pop_fallbacks("fused")
    monkeypatch.setenv("HYDRAGNN_EGCL_FUSED", "1")
    monkeypatch.setattr("hydragnn_tpu.ops.egcl_mp.EGCL_H_LIMIT", 1)
    model.apply({"params": variables["params"]}, g, train=False)
    fbs = tp.pop_fallbacks("fused")
    assert fbs and fbs[0]["reason"] == "width_gate"
    assert fbs[0]["arch"] == "EGNN"


def test_dispatch_tally_counts_cgcnn(monkeypatch):
    """The cgcnn dispatch site: marker-carrying batch tallies :fused,
    marker-less :scatter, and a requested-but-denied width emits the
    unified fused_fallback with arch=CGCNN."""
    from hydragnn_tpu.telemetry import pipeline as tp

    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", "fused")
    b = _batch(seed=25)
    cfg = _model_cfg("CGCNN")
    model = create_model(cfg)
    before = tp.dispatch_snapshot()
    variables = model.init({"params": jax.random.PRNGKey(0),
                            "dropout": jax.random.PRNGKey(1)}, b,
                           train=False)
    model.apply({"params": variables["params"],
                 "batch_stats": variables.get("batch_stats", {})},
                b, train=False)
    delta = tp.dispatch_delta(before, tp.dispatch_snapshot())
    assert delta.get("cgcnn:fused", 0) > 0

    ex = dict(b.extras)
    del ex["edge_perm_sender"]
    b_plain = b.replace(extras=ex)
    tp.pop_fallbacks("fused")
    before = tp.dispatch_snapshot()
    model.apply({"params": variables["params"],
                 "batch_stats": variables.get("batch_stats", {})},
                b_plain, train=False)
    delta = tp.dispatch_delta(before, tp.dispatch_snapshot())
    assert delta.get("cgcnn:scatter", 0) > 0
    fbs = tp.pop_fallbacks("fused")
    assert fbs and fbs[0]["arch"] == "CGCNN"
    assert fbs[0]["reason"] == "no_sender_perm"
