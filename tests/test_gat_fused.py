"""One-pass fused GATv2 attention (ops/gat_mp.py) vs the composed segment-op
path: forward parity, gradient parity, dropout-bit parity, and model-level
equivalence — interpret mode on CPU, same collate invariants as production.
"""

import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from hydragnn_tpu.graph import segment
from hydragnn_tpu.graph.batch import GraphSample, HeadSpec, PadSpec, collate
from hydragnn_tpu.graph.neighborlist import radius_graph
from hydragnn_tpu.ops.gat_mp import gat_edge_attention


H, F = 4, 8
SLOPE = 0.05


def _batch(n_graphs=6, nodes=9, seed=0):
    rng = np.random.RandomState(seed)
    samples = []
    for _ in range(n_graphs):
        pos = rng.rand(nodes, 3).astype(np.float32) * 2.2
        samples.append(GraphSample(
            x=rng.rand(nodes, 2).astype(np.float32), pos=pos,
            edge_index=radius_graph(pos, 1.4, 8),
            graph_y=rng.rand(1).astype(np.float32)))
    pad = PadSpec.for_batch(n_graphs, nodes,
                            max(s.num_edges for s in samples))
    # collate attaches edge_perm_sender only under the fused backend
    prev = os.environ.get("HYDRAGNN_AGGR_BACKEND")
    os.environ["HYDRAGNN_AGGR_BACKEND"] = "fused"
    try:
        return collate(samples, pad, [HeadSpec("e", "graph", 1)])
    finally:
        if prev is None:
            os.environ.pop("HYDRAGNN_AGGR_BACKEND", None)
        else:
            os.environ["HYDRAGNN_AGGR_BACKEND"] = prev


def _inputs(g, seed=1):
    rng = np.random.RandomState(seed)
    n = g.x.shape[0]
    xl = jnp.asarray(rng.randn(n, H * F), jnp.float32)
    xr = jnp.asarray(rng.randn(n, H * F), jnp.float32)
    att = jnp.asarray(rng.randn(H, F), jnp.float32) * 0.5
    rows = jnp.arange(H * F)
    att_mat = jnp.zeros((H * F, H), jnp.float32).at[rows, rows // F].set(
        att.reshape(-1))
    return xl, xr, att, att_mat


def _reference_partials(xl, xr, att, g, b_edge):
    """Composed-op computation of (acc, m, d) as defined by the kernel:
    real incident edges only, numerator carries the dropout bits."""
    n = xl.shape[0]
    src, dst = g.senders, g.receivers
    z = jax.nn.leaky_relu(xl[src] + xr[dst], SLOPE)
    e = jnp.sum(z.reshape(-1, H, F) * att[None], axis=-1)      # [E, H]
    e = jnp.where(g.edge_mask[:, None] > 0, e, -1e30)
    m = segment.segment_max(e, dst, n)                          # 0 if empty
    deg = segment.degree(dst, n, g.edge_mask)
    m = jnp.where(deg[:, None] > 0, m, -1e30)
    # production's composed path stop-gradients the max shift too
    # (models/gat.py) — shift invariance makes this exact
    m = jax.lax.stop_gradient(m)
    p = jnp.exp(e - m[dst]) * g.edge_mask[:, None]
    d = jax.ops.segment_sum(p, dst, n)
    pb = p * b_edge
    w = jnp.repeat(pb, F, axis=1)
    acc = jax.ops.segment_sum(xl[src] * w, dst, n)
    return acc, m, d


def test_fused_forward_matches_composed():
    g = _batch()
    xl, xr, att, att_mat = _inputs(g)
    b = jnp.ones((g.senders.shape[0], H), jnp.float32)
    acc, m, d = gat_edge_attention(
        xl, xr, att_mat, g.senders, g.receivers,
        g.extras["edge_perm_sender"], g.edge_mask, b, (SLOPE, F))
    acc_r, m_r, d_r = _reference_partials(xl, xr, att, g, b)
    deg = np.asarray(segment.degree(g.receivers, xl.shape[0], g.edge_mask))
    has = deg > 0
    np.testing.assert_allclose(np.asarray(m)[has], np.asarray(m_r)[has],
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(d)[has], np.asarray(d_r)[has],
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(acc), np.asarray(acc_r),
                               rtol=1e-4, atol=1e-4)


def test_fused_forward_dropout_bits():
    g = _batch(seed=3)
    xl, xr, att, att_mat = _inputs(g, seed=4)
    rng = np.random.RandomState(7)
    b = jnp.asarray(
        (rng.rand(g.senders.shape[0], H) > 0.3).astype(np.float32) / 0.7)
    acc, m, d = gat_edge_attention(
        xl, xr, att_mat, g.senders, g.receivers,
        g.extras["edge_perm_sender"], g.edge_mask, b, (SLOPE, F))
    acc_r, _, d_r = _reference_partials(xl, xr, att, g, b)
    # d ignores dropout (softmax-then-dropout); acc carries the bits
    np.testing.assert_allclose(np.asarray(acc), np.asarray(acc_r),
                               rtol=1e-4, atol=1e-4)
    np.testing.assert_allclose(
        np.asarray(d)[np.asarray(d_r) > 0],
        np.asarray(d_r)[np.asarray(d_r) > 0], rtol=1e-5, atol=1e-5)


def _merge_loss(acc, m, d, xl):
    """The production-style self-loop merge (models/gat.py): SHIFT-INVARIANT
    in m, which is what makes stop_gradient(m) exact — a non-invariant
    normalization (e.g. acc / max(d, 1)) would make the frozen-m gradient
    genuinely differ from autodiff-through-segment_max."""
    m = jax.lax.stop_gradient(m)
    m_t = jax.lax.stop_gradient(jnp.maximum(m, 0.0))  # e_self = 0
    r_e = jnp.exp(m - m_t)
    r_s = jnp.exp(-m_t)
    d_t = d * r_e + r_s
    out = (acc * jnp.repeat(r_e, F, axis=1)
           + jnp.repeat(r_s, F, axis=1) * xl) / jnp.repeat(d_t, F, axis=1)
    w = jnp.arange(out.size, dtype=jnp.float32).reshape(out.shape) * 1e-3
    return jnp.sum(out * w)


def _loss_fused(xl, xr, att_mat, g, b):
    acc, m, d = gat_edge_attention(
        xl, xr, att_mat, g.senders, g.receivers,
        g.extras["edge_perm_sender"], g.edge_mask, b, (SLOPE, F))
    return _merge_loss(acc, m, d, xl)


def _loss_composed(xl, xr, att_mat, g, b):
    att = att_mat[jnp.arange(H * F), jnp.arange(H * F) // F].reshape(H, F)
    acc, m, d = _reference_partials(xl, xr, att, g, b)
    return _merge_loss(acc, m, d, xl)


def test_fused_gradients_match_composed():
    g = _batch(seed=5)
    xl, xr, att, att_mat = _inputs(g, seed=6)
    rng = np.random.RandomState(11)
    b = jnp.asarray(
        (rng.rand(g.senders.shape[0], H) > 0.2).astype(np.float32) / 0.8)
    gf = jax.grad(_loss_fused, argnums=(0, 1, 2))(xl, xr, att_mat, g, b)
    gc = jax.grad(_loss_composed, argnums=(0, 1, 2))(xl, xr, att_mat, g, b)
    # tolerance sized for the CPU backend's reduced-precision (oneDNN)
    # matmuls that both implementations ride in interpret mode
    for a, bb, name in zip(gf[:2], gc[:2], ("dxl", "dxr")):
        np.testing.assert_allclose(
            np.asarray(a), np.asarray(bb), rtol=2e-3, atol=2e-3,
            err_msg=name)
    # att_mat grad: only the block-diagonal entries reach the att
    # parameter (the model builds att_mat by scattering att onto the
    # diagonal); the kernel's dense cotangent legitimately carries
    # off-diagonal sensitivities the composed extraction zeroes
    rows = np.arange(H * F)
    np.testing.assert_allclose(
        np.asarray(gf[2])[rows, rows // F],
        np.asarray(gc[2])[rows, rows // F],
        rtol=2e-3, atol=2e-3, err_msg="datt diagonal")


def test_model_level_gradients_match(monkeypatch):
    """Full GATStack param gradients: fused vs composed (dropout off)."""
    from hydragnn_tpu.models.base import GraphHeadCfg, ModelConfig
    from hydragnn_tpu.models.create import create_model

    g = _batch(seed=9)
    cfg = ModelConfig(
        model_type="GAT", input_dim=2, hidden_dim=8, output_dim=(1,),
        output_type=("graph",), graph_head=GraphHeadCfg(1, 8, 1, (8,)),
        node_head=None, task_weights=(1.0,), num_conv_layers=2,
        dropout=0.0)
    model = create_model(cfg)
    monkeypatch.setenv("HYDRAGNN_GAT_FUSED", "1")
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        g, train=False)

    def loss(params, train):
        out = model.apply(
            {"params": params, "batch_stats": variables.get("batch_stats", {})},
            g, train=train,
            rngs={"dropout": jax.random.PRNGKey(2)} if train else None,
            mutable=["batch_stats"] if train else False)
        out = out[0] if train else out
        return sum(jnp.sum(o * o) for o in out)

    gf = jax.grad(lambda p: loss(p, True))(variables["params"])
    monkeypatch.setenv("HYDRAGNN_GAT_FUSED", "0")
    gp = jax.grad(lambda p: loss(p, True))(variables["params"])
    flat_f = jax.tree_util.tree_leaves_with_path(gf)
    flat_p = dict(jax.tree_util.tree_leaves_with_path(gp))
    for path, leaf in flat_f:
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat_p[path]), rtol=3e-3, atol=3e-3,
            err_msg=str(path))


def test_model_level_fused_equals_composed(monkeypatch):
    """Full GATStack forward: fused path (env-forced on) vs composed path
    (env-forced off) on the same params/batch must agree in eval mode."""
    from hydragnn_tpu.models.base import GraphHeadCfg, ModelConfig
    from hydragnn_tpu.models.create import create_model

    g = _batch(seed=8)
    cfg = ModelConfig(
        model_type="GAT", input_dim=2, hidden_dim=8, output_dim=(1,),
        output_type=("graph",), graph_head=GraphHeadCfg(1, 8, 1, (8,)),
        node_head=None, task_weights=(1.0,), num_conv_layers=2,
        dropout=0.0)
    model = create_model(cfg)
    monkeypatch.setenv("HYDRAGNN_GAT_FUSED", "1")
    params = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        g, train=False)
    out_fused = model.apply(params, g, train=False)
    monkeypatch.setenv("HYDRAGNN_GAT_FUSED", "0")
    out_plain = model.apply(params, g, train=False)
    for a, b in zip(out_fused, out_plain):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4)


def test_tiled_matches_untiled(monkeypatch):
    """gat_edge_attention_tiled with a forced-small FUSED_HF_LIMIT (heads
    split into groups) must reproduce the one-call kernel: attention is
    independent per head, so the group slicing changes launches, not
    math — forward partials AND gradients."""
    import hydragnn_tpu.ops.gat_mp as gat_mp
    from hydragnn_tpu.ops.gat_mp import gat_edge_attention_tiled

    g = _batch(seed=13)
    xl, xr, att, att_mat = _inputs(g, seed=14)
    b = jnp.ones((g.senders.shape[0], H), jnp.float32)
    perm = g.extras["edge_perm_sender"]

    ref = gat_edge_attention(xl, xr, att_mat, g.senders, g.receivers,
                             perm, g.edge_mask, b, (SLOPE, F))
    assert H * F > 2 * F  # the forced limit below actually splits
    monkeypatch.setattr(gat_mp, "FUSED_HF_LIMIT", 2 * F)
    assert gat_mp._head_groups(H, F) == [2, 2]
    tiled = gat_edge_attention_tiled(
        xl, xr, att_mat, g.senders, g.receivers, perm, g.edge_mask, b,
        (SLOPE, F))
    for a, r, name in zip(tiled, ref, ("acc", "m", "d")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=1e-4, atol=1e-4, err_msg=name)

    def loss_tiled(xl_, xr_, am_):
        acc, m, d = gat_edge_attention_tiled(
            xl_, xr_, am_, g.senders, g.receivers, perm, g.edge_mask, b,
            (SLOPE, F))
        return _merge_loss(acc, m, d, xl_)

    gt = jax.grad(loss_tiled, argnums=(0, 1, 2))(xl, xr, att_mat)
    monkeypatch.setattr(gat_mp, "FUSED_HF_LIMIT", 1024)
    gu = jax.grad(loss_tiled, argnums=(0, 1, 2))(xl, xr, att_mat)
    for a, r, name in zip(gt[:2], gu[:2], ("dxl", "dxr")):
        np.testing.assert_allclose(np.asarray(a), np.asarray(r),
                                   rtol=2e-3, atol=2e-3, err_msg=name)
    # datt: compare the block-diagonal entries only — the one-call kernel
    # returns dense cotangents for att_mat's structurally-zero cross-group
    # entries that the tiled slicing (correctly) never touches, and the
    # model consumes only the diagonal (see test_fused_gradients_match_
    # composed)
    rows = np.arange(H * F)
    np.testing.assert_allclose(
        np.asarray(gt[2])[rows, rows // F],
        np.asarray(gu[2])[rows, rows // F],
        rtol=2e-3, atol=2e-3, err_msg="datt diagonal")


def test_wide_heads_stay_fused_via_head_tiling(monkeypatch):
    """hf = heads*hidden above FUSED_HF_LIMIT now STAYS on the fused path
    by tiling over balanced head groups (the pre-tiling behavior was a
    silent composed-path fallback at h256 x 6 heads — the GAT item of
    round-5 VERDICT weak-2) and must match the composed path numerically.
    The limit is monkeypatched small so the tier-1 test exercises the
    tiled path at toy width."""
    import hydragnn_tpu.ops.gat_mp as gat_mp
    from hydragnn_tpu.models.base import GraphHeadCfg, ModelConfig
    from hydragnn_tpu.models.create import create_model
    from hydragnn_tpu.models.gat import GATv2Conv

    calls = []
    orig = GATv2Conv._fused_attention

    def spy(self, *a, **k):
        calls.append(self.out_dim)
        return orig(self, *a, **k)

    monkeypatch.setattr(GATv2Conv, "_fused_attention", spy)
    monkeypatch.setenv("HYDRAGNN_GAT_FUSED", "1")
    # hidden=8 x 6 heads = hf 48 > 16 = limit -> 3 groups of 2 heads;
    # f=8 <= 16 keeps the per-head gate satisfied.  ONE patch point:
    # the dispatcher queries gat_mp's live limit (fused_head_width_ok)
    monkeypatch.setattr(gat_mp, "FUSED_HF_LIMIT", 16)

    g = _batch(seed=11)
    cfg = ModelConfig(
        model_type="GAT", input_dim=2, hidden_dim=8, output_dim=(1,),
        output_type=("graph",), graph_head=GraphHeadCfg(1, 8, 1, (8,)),
        node_head=None, task_weights=(1.0,), num_conv_layers=2,
        dropout=0.0)
    model = create_model(cfg)
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        g, train=False)
    out_fused = model.apply(
        {"params": variables["params"],
         "batch_stats": variables.get("batch_stats", {})}, g, train=False)
    assert calls, "wide config must stay on the fused (tiled) path"
    monkeypatch.setenv("HYDRAGNN_GAT_FUSED", "0")
    out_plain = model.apply(
        {"params": variables["params"],
         "batch_stats": variables.get("batch_stats", {})}, g, train=False)
    for a, bb in zip(out_fused, out_plain):
        np.testing.assert_allclose(np.asarray(a), np.asarray(bb),
                                   rtol=5e-4, atol=5e-4)


def test_single_over_wide_head_falls_back(monkeypatch):
    """Only a SINGLE head wider than FUSED_HF_LIMIT still forces the
    composed path (no group can shrink below one head)."""
    import hydragnn_tpu.ops.gat_mp as gat_mp
    from hydragnn_tpu.models.base import GraphHeadCfg, ModelConfig
    from hydragnn_tpu.models.create import create_model
    from hydragnn_tpu.models.gat import GATv2Conv

    calls = []
    orig = GATv2Conv._fused_attention

    def spy(self, *a, **k):
        calls.append(self.out_dim)
        return orig(self, *a, **k)

    monkeypatch.setattr(GATv2Conv, "_fused_attention", spy)
    monkeypatch.setenv("HYDRAGNN_GAT_FUSED", "1")
    monkeypatch.setattr(gat_mp, "FUSED_HF_LIMIT", 4)  # < f = 8

    g = _batch(seed=12)
    cfg = ModelConfig(
        model_type="GAT", input_dim=2, hidden_dim=8, output_dim=(1,),
        output_type=("graph",), graph_head=GraphHeadCfg(1, 8, 1, (8,)),
        node_head=None, task_weights=(1.0,), num_conv_layers=2,
        dropout=0.0)
    model = create_model(cfg)
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        g, train=False)
    out = model.apply(
        {"params": variables["params"],
         "batch_stats": variables.get("batch_stats", {})}, g, train=False)
    assert np.all(np.isfinite(np.asarray(out[0])))
    assert calls == []  # every layer stayed on the composed path
