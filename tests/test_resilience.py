"""Fault-tolerant training (hydragnn_tpu/resilience, docs/RESILIENCE.md):
in-jit non-finite step guards on all three step paths, preemption-aware
checkpointing with true mid-run resume (crash-and-resume bit-parity), and
the chaos/fault-injection harness + checkpoint retry/degradation ladder.
"""

import json
import os
import signal

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hydragnn_tpu.data.dataloader import GraphDataLoader, pad_spec_for
from hydragnn_tpu.graph.batch import GraphSample, HeadSpec, PadSpec, collate
from hydragnn_tpu.graph.neighborlist import radius_graph
from hydragnn_tpu.models.base import GraphHeadCfg, ModelConfig
from hydragnn_tpu.models.create import create_model
from hydragnn_tpu.parallel.mesh import stack_batches
from hydragnn_tpu.resilience import (
    Chaos,
    NonFiniteGuardMonitor,
    NonFiniteTrainingError,
    PreemptionHandler,
    load_resume_bundle,
    resume_dir,
    with_retries,
)
from hydragnn_tpu.telemetry import MetricsLogger
from hydragnn_tpu.train.optimizer import select_optimizer
from hydragnn_tpu.train.trainer import (
    create_train_state,
    make_scan_train_step,
    make_train_step,
    train_validate_test,
)


def _model():
    cfg = ModelConfig(
        model_type="SAGE", input_dim=1, hidden_dim=8, output_dim=(1,),
        output_type=("graph",), graph_head=GraphHeadCfg(1, 8, 1, (8,)),
        node_head=None, task_weights=(1.0,), num_conv_layers=2)
    return cfg, create_model(cfg)


def _samples(n, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        pos = rng.rand(10, 3).astype(np.float32) * 2.0
        x = rng.rand(10, 1).astype(np.float32)
        ei = radius_graph(pos, 1.2, 10)
        out.append(GraphSample(x=x, pos=pos, edge_index=ei,
                               graph_y=x.sum(keepdims=True)[0], node_y=x))
    return out


def _batch(seed=0, n_graphs=4):
    samples = _samples(n_graphs, seed)
    return collate(samples, PadSpec.for_batch(n_graphs, 10, 90),
                   [HeadSpec("e", "graph", 1)])


def _nan_batch(b):
    return b.replace(x=jnp.full(b.x.shape, jnp.nan, b.x.dtype))


def _leaves_equal(a, b):
    la = jax.tree_util.tree_leaves(jax.device_get(a))
    lb = jax.tree_util.tree_leaves(jax.device_get(b))
    return all(np.array_equal(np.asarray(x), np.asarray(y))
               for x, y in zip(la, lb))


# ---------------------------------------------------------------------------
# In-jit non-finite guards: local jit, scanned-K, mesh-DP
# ---------------------------------------------------------------------------


def test_nonfinite_guard_local_skips_and_recovers():
    cfg, model = _model()
    opt = select_optimizer({"type": "AdamW", "learning_rate": 1e-3})
    b = _batch()
    s0 = create_train_state(model, b, opt)
    step = jax.jit(make_train_step(model, cfg, opt, nonfinite_guard=True))

    s1, m1 = step(s0, _nan_batch(b))
    assert float(m1["skipped"]) == 1.0
    # skipped steps contribute NOTHING to epoch accumulators
    assert float(m1["loss"]) == 0.0 and float(m1["num_graphs"]) == 0.0
    # params, opt state and batch stats all revert; the step counter counts
    # the ATTEMPT (dropout fold-in stays aligned with the batch stream)
    assert _leaves_equal(s1.params, s0.params)
    assert _leaves_equal(s1.opt_state, s0.opt_state)
    assert int(s1.step) == 1

    s2, m2 = step(s1, b)
    assert float(m2["skipped"]) == 0.0
    assert jnp.isfinite(m2["loss"])
    assert not _leaves_equal(s2.params, s1.params)

    # with telemetry metrics on, a skipped step's norms are sanitized —
    # a raw NaN would poison the graph-weighted scan merge (NaN * 0)
    tstep = jax.jit(make_train_step(model, cfg, opt,
                                    telemetry_metrics=True,
                                    nonfinite_guard=True))
    _, mt = tstep(s0, _nan_batch(b))
    for k in ("grad_norm", "param_norm", "update_norm"):
        assert np.isfinite(float(mt[k])), k


def test_nonfinite_guard_scan_counts_skipped_steps():
    cfg, model = _model()
    opt = select_optimizer({"type": "AdamW", "learning_rate": 1e-3})
    b0, b1 = _batch(seed=1), _batch(seed=2)
    s0 = create_train_state(model, b0, opt)

    # clean step then NaN step inside one scanned executable: the merged
    # metrics count 1 skipped step, and the final params equal the params
    # after the clean step alone
    scan = jax.jit(make_scan_train_step(model, cfg, opt, None, 2,
                                        nonfinite_guard=True))
    s_scan, ms = scan(s0, stack_batches([b0, _nan_batch(b1)]))
    assert float(ms["skipped"]) == 1.0
    assert float(ms["num_graphs"]) == 4.0  # only the clean step's graphs

    ref_step = jax.jit(make_train_step(model, cfg, opt,
                                       nonfinite_guard=True))
    s_ref, _ = ref_step(s0, b0)
    assert _leaves_equal(s_scan.params, s_ref.params)


def test_nonfinite_guard_mesh_dp_skips_whole_step():
    from hydragnn_tpu.parallel.mesh import (
        make_dp_train_step,
        make_mesh,
        replicate_state,
    )

    cfg, model = _model()
    opt = select_optimizer({"type": "AdamW", "learning_rate": 1e-3})
    n_dev = len(jax.devices())
    mesh = make_mesh()
    batches = [_batch(seed=i) for i in range(n_dev)]
    s0 = create_train_state(model, batches[0], opt)
    step = make_dp_train_step(model, cfg, opt, mesh, nonfinite_guard=True)

    # NaN on ONE device's shard: the gradient pmean spreads it, the
    # replicated flag trips, and every replica keeps the old params
    batches[0] = _nan_batch(batches[0])
    s1, m = step(replicate_state(s0, mesh), stack_batches(batches))
    assert float(m["skipped"]) == 1.0
    assert float(m["num_graphs"]) == 0.0
    assert _leaves_equal(s1.params, s0.params)

    # clean stacked batch trains normally
    clean = stack_batches([_batch(seed=10 + i) for i in range(n_dev)])
    s2, m2 = step(s1, clean)
    assert float(m2["skipped"]) == 0.0
    assert not _leaves_equal(s2.params, s0.params)


def test_guard_off_traces_unchanged_program():
    """Disabled guard must be FREE: no finiteness ops, no skipped metric —
    the traced program is the pre-guard program."""
    cfg, model = _model()
    opt = select_optimizer({"type": "AdamW", "learning_rate": 1e-3})
    b = _batch()
    s0 = create_train_state(model, b, opt)
    off = jax.jit(make_train_step(model, cfg, opt)).lower(s0, b).as_text()
    on = jax.jit(make_train_step(model, cfg, opt, nonfinite_guard=True)
                 ).lower(s0, b).as_text()
    assert "is_finite" not in off
    assert "is_finite" in on
    _, m = jax.jit(make_train_step(model, cfg, opt))(s0, b)
    assert "skipped" not in m


def test_guard_monitor_aborts_with_diagnostic_dump(tmp_path):
    dump = str(tmp_path / "nonfinite_abort.json")
    tele = MetricsLogger.disabled()
    mon = NonFiniteGuardMonitor(max_consecutive=3, poll_every=1,
                                dump_path=dump, telemetry=tele)
    b = _batch()
    good = {"skipped": jnp.zeros(()), "loss": jnp.ones(()),
            "grad_norm": jnp.ones(())}
    bad = {"skipped": jnp.ones(()), "loss": jnp.full((), jnp.nan),
           "grad_norm": jnp.full((), jnp.inf)}
    mon.on_step(bad, b)
    mon.on_step(good, b)  # streak broken
    mon.on_step(bad, b)
    mon.on_step(bad, b)
    with pytest.raises(NonFiniteTrainingError):
        mon.on_step(bad, b)
    d = json.load(open(dump))
    assert d["consecutive_bad_steps"] == 3
    assert d["offending_batch_shape"]["x"] == list(b.x.shape)
    assert len(d["history"]) == 5
    assert any(h["skipped"] == 0 for h in d["history"])
    assert tele.health_counts.get("nonfinite_abort") == 1


# ---------------------------------------------------------------------------
# trainer-level crash-and-resume bit-parity
# ---------------------------------------------------------------------------


class _Loaders:
    """Deterministic loader triple rebuilt per run (shuffle replays from
    set_epoch, so two runs over the same construction are identical)."""

    def __init__(self, n_train=32, batch_size=8, seed=7):
        self.heads = [HeadSpec("e", "graph", 1)]
        all_s = _samples(n_train + 16, seed=5)
        self.pad = pad_spec_for(all_s, batch_size)
        self.mk = lambda split, shuffle: GraphDataLoader(
            split, self.heads, batch_size, pad_spec=self.pad,
            shuffle=shuffle, seed=seed)
        self.train_s = all_s[:n_train]
        self.val_s = all_s[n_train:n_train + 8]
        self.test_s = all_s[n_train + 8:]

    def __call__(self):
        return (self.mk(self.train_s, True), self.mk(self.val_s, False),
                self.mk(self.test_s, False))


def _run(loaders, tmp_path, name, num_epoch=3, use_mesh_dp=False,
         resume_meta=None, state=None, training_extra=None, lr=0.01,
         telemetry=None):
    cfg, model = _model()
    opt = select_optimizer({"type": "AdamW", "learning_rate": lr})
    train_l, val_l, test_l = loaders()
    if state is None:
        state = create_train_state(model, next(iter(train_l)), opt)
    training = {"num_epoch": num_epoch, **(training_extra or {})}
    return train_validate_test(
        model, cfg, state, opt, train_l, val_l, test_l,
        {"Training": training, "Variables_of_interest": {"output_names": ["e"]}},
        log_name=name, logs_dir=str(tmp_path), use_mesh_dp=use_mesh_dp,
        resume_meta=resume_meta, telemetry=telemetry)


def _fresh_skeleton(loaders, lr=0.01):
    cfg, model = _model()
    opt = select_optimizer({"type": "AdamW", "learning_rate": lr})
    train_l, _, _ = loaders()
    return create_train_state(model, next(iter(train_l)), opt)


@pytest.mark.parametrize("use_mesh_dp", [False, True],
                         ids=["local", "mesh_dp"])
def test_crash_and_resume_bit_parity(tmp_path, monkeypatch, use_mesh_dp):
    """A run preempted at an arbitrary mid-epoch step and resumed must
    produce params IDENTICAL to the uninterrupted run: the bundle restores
    epoch/step/scheduler state and the resumed epoch replays the
    deterministic shuffle, skipping already-seen dispatch units."""
    monkeypatch.delenv("HYDRAGNN_CHAOS_PREEMPT_STEP", raising=False)
    if use_mesh_dp:
        # 8 virtual devices stack 8 micro-batches per dispatch unit
        loaders = _Loaders(n_train=64, batch_size=4)
        preempt_at = 3  # of 2 units/epoch x 3 epochs -> mid-epoch 1
    else:
        loaders = _Loaders(n_train=32, batch_size=8)
        preempt_at = 6  # of 4 units/epoch x 3 epochs -> mid-epoch 1

    state_a, hist_a = _run(loaders, tmp_path, "uninterrupted",
                           use_mesh_dp=use_mesh_dp)
    assert "preempted" not in hist_a

    # chaos-simulated preemption: the handler flag is raised exactly as a
    # SIGTERM would, at a deterministic dispatch index
    monkeypatch.setenv("HYDRAGNN_CHAOS_PREEMPT_STEP", str(preempt_at))
    state_b, hist_b = _run(loaders, tmp_path, "preempted",
                           use_mesh_dp=use_mesh_dp)
    assert hist_b.get("preempted") is True
    monkeypatch.delenv("HYDRAGNN_CHAOS_PREEMPT_STEP")

    rdir = resume_dir(str(tmp_path), "preempted")
    bundle = load_resume_bundle(_fresh_skeleton(loaders), rdir)
    assert bundle is not None
    state_r, meta = bundle
    assert meta["epoch"] == 1
    assert meta["items_consumed"] == preempt_at - (2 if use_mesh_dp else 4)
    state_c, hist_c = _run(loaders, tmp_path, "preempted",
                           use_mesh_dp=use_mesh_dp,
                           resume_meta=meta, state=state_r)
    assert "preempted" not in hist_c
    assert len(hist_c["val"]) == 3  # saved history + resumed epochs

    assert _leaves_equal(state_c.params, state_a.params)
    assert _leaves_equal(state_c.opt_state, state_a.opt_state)
    assert int(jax.device_get(state_c.step)) == int(
        jax.device_get(state_a.step))


def test_walltime_stop_saves_bundle_and_resumes(tmp_path, monkeypatch):
    """SLURM walltime exit saves the full resume bundle (satellite: no work
    lost since the last full_state_checkpoint) and `continue` resumes at
    the right epoch with bit parity."""
    loaders = _Loaders()
    state_a, _ = _run(loaders, tmp_path, "nowall")

    import hydragnn_tpu.utils.slurm as slurm

    calls = {"n": 0}

    def fake_check(epoch_seconds, safety_factor=2.0):
        calls["n"] += 1
        return False  # never enough time for another epoch

    monkeypatch.setenv("SLURM_JOB_ID", "12345")
    monkeypatch.setattr(slurm, "check_remaining", fake_check)
    state_b, hist_b = _run(loaders, tmp_path, "walled")
    assert calls["n"] == 1 and hist_b.get("preempted") is True
    assert len(hist_b["train"]) == 1  # stopped after epoch 0
    monkeypatch.delenv("SLURM_JOB_ID")

    bundle = load_resume_bundle(
        _fresh_skeleton(loaders), resume_dir(str(tmp_path), "walled"))
    assert bundle is not None
    state_r, meta = bundle
    assert meta["epoch"] == 1 and meta["items_consumed"] == 0
    assert meta["reason"] == "walltime"
    state_c, _ = _run(loaders, tmp_path, "walled", resume_meta=meta,
                      state=state_r)
    assert _leaves_equal(state_c.params, state_a.params)


def test_chaos_nan_batch_skipped_and_run_converges(tmp_path, monkeypatch):
    """An injected NaN batch is skipped (telemetry counts it via the
    step_skipped health event) and the run converges on clean batches."""
    monkeypatch.setenv("HYDRAGNN_CHAOS_NAN_STEP", "2")
    monkeypatch.setenv("HYDRAGNN_TELEMETRY", "1")
    monkeypatch.setenv("HYDRAGNN_TELEMETRY_SINKS", "jsonl")
    tdir = str(tmp_path / "tele")
    monkeypatch.setenv("HYDRAGNN_TELEMETRY_DIR", tdir)
    loaders = _Loaders()
    state, hist = _run(loaders, tmp_path, "nanrun", num_epoch=4,
                       training_extra={"nonfinite_guard": 1})
    assert all(np.isfinite(hist["train"]))
    assert hist["train"][-1] < hist["train"][0]
    for leaf in jax.tree_util.tree_leaves(jax.device_get(state.params)):
        assert np.isfinite(np.asarray(leaf)).all()

    records = [json.loads(l) for l in
               open(os.path.join(tdir, "events.jsonl")) if l.strip()]
    skipped = [r for r in records if r.get("event") == "health"
               and r.get("kind") == "step_skipped"]
    assert len(skipped) == 1
    manifest = [r for r in records if r.get("event") == "manifest"][-1]
    assert manifest["health"]["step_skipped"] == 1
    steps = [r for r in records if r.get("event") == "step"]
    assert sum(r.get("skipped", 0) for r in steps) == 1


def test_all_nan_stream_aborts_with_dump(tmp_path, monkeypatch):
    """N consecutive bad steps abort with a diagnostic dump; params stay
    finite (every bad update was suppressed in-jit)."""
    monkeypatch.setenv("HYDRAGNN_CHAOS_NAN_STEP", "1+")
    loaders = _Loaders()
    with pytest.raises(NonFiniteTrainingError, match="consecutive"):
        _run(loaders, tmp_path, "allnan",
             training_extra={"nonfinite_guard": 1,
                             "guard_max_consecutive": 3,
                             "guard_poll_every": 1})
    dump = json.load(open(tmp_path / "allnan" / "nonfinite_abort.json"))
    assert dump["consecutive_bad_steps"] >= 3
    assert dump["history"][-1]["skipped"] == 1


# ---------------------------------------------------------------------------
# preemption handler, chaos parsing, checkpoint I/O ladder
# ---------------------------------------------------------------------------


def test_preemption_handler_sigterm_roundtrip():
    h = PreemptionHandler().install()
    try:
        assert not h.poll()
        os.kill(os.getpid(), signal.SIGTERM)
        assert h.poll() and h.stop_requested
    finally:
        h.uninstall()
    # handlers restored: a fresh handler starts clean
    h2 = PreemptionHandler()
    h2.request()
    assert h2.poll()


def test_chaos_parsing_and_one_shot_preempt(monkeypatch):
    for var in ("HYDRAGNN_CHAOS_NAN_STEP", "HYDRAGNN_CHAOS_PREEMPT_STEP",
                "HYDRAGNN_CHAOS_CKPT_FAILS"):
        monkeypatch.delenv(var, raising=False)
    assert Chaos.from_env() is None
    assert Chaos.from_env({"nan_step": ""}) is None

    c = Chaos.from_env({"nan_step": "2,4+", "preempt_step": 3,
                        "ckpt_fails": 1})
    b = _batch()
    seen = []
    for _ in range(5):
        g = c.on_train_dispatch(b)
        seen.append(bool(np.isnan(np.asarray(g.x)).any()))
    assert seen == [False, True, False, True, True]
    # fires exactly once, at/after the armed dispatch
    assert c.preempt_now() and not c.preempt_now()
    with pytest.raises(OSError, match="chaos"):
        c.ckpt_attempt()
    c.ckpt_attempt()  # budget exhausted -> clean


def test_ckpt_retry_backoff_and_degradation():
    tele = MetricsLogger.disabled()
    calls = {"n": 0}

    def ok_fn():
        calls["n"] += 1

    # two injected failures, then success on the third attempt
    assert with_retries(ok_fn, retries=3, backoff=0.0, telemetry=tele,
                        chaos=Chaos(ckpt_fails=2))
    assert calls["n"] == 1
    assert tele.health_counts["ckpt_retry"] == 2

    def boom():
        raise OSError("disk on fire")

    # graceful degradation: warn, count, keep going
    with pytest.warns(UserWarning, match="disk on fire"):
        assert not with_retries(boom, retries=1, backoff=0.0,
                                telemetry=tele, on_fail="warn")
    assert tele.health_counts["ckpt_giveup"] == 1
    with pytest.raises(OSError):
        with_retries(boom, retries=0, backoff=0.0)


def test_periodic_checkpoint_failure_degrades_not_crashes(tmp_path,
                                                          monkeypatch):
    """A filesystem that keeps failing must cost the checkpoints, not the
    run (acceptance: warn and keep training)."""
    monkeypatch.setenv("HYDRAGNN_CHAOS_CKPT_FAILS", "99")
    loaders = _Loaders()
    with pytest.warns(UserWarning, match="periodic full-state checkpoint"):
        _, hist = _run(loaders, tmp_path, "degraded", num_epoch=2,
                       training_extra={"full_state_checkpoint": 1,
                                       "ckpt_backoff": 0.0})
    assert len(hist["train"]) == 2  # trained through both epochs
    from hydragnn_tpu.utils.checkpoint import latest_step

    assert latest_step(str(tmp_path / "degraded" / "orbax")) is None


# ---------------------------------------------------------------------------
# checkpoint manager reuse + atomic writes + bundle validity
# ---------------------------------------------------------------------------


def _tiny_state():
    from hydragnn_tpu.train.trainer import TrainState

    return TrainState(
        step=jnp.asarray(3, jnp.int32),
        params={"w": jnp.arange(4, dtype=jnp.float32)},
        batch_stats={"m": jnp.ones((2,), jnp.float32)},
        opt_state={"mu": jnp.zeros((4,), jnp.float32)},
    )


def test_checkpoint_manager_reused_and_notfound_no_leak(tmp_path):
    from hydragnn_tpu.utils import checkpoint as ckpt

    d = str(tmp_path / "orbax")
    state = _tiny_state()
    ckpt.save_checkpoint(state, d)
    m1 = ckpt._manager(d)
    ckpt.save_checkpoint(state, d, step=7)
    assert ckpt._manager(d) is m1  # one manager per run, reused
    restored = ckpt.restore_checkpoint(_tiny_state(), d)
    assert _leaves_equal(restored, state)

    empty = str(tmp_path / "empty")
    for _ in range(3):
        with pytest.raises(FileNotFoundError):
            ckpt.restore_checkpoint(state, empty)
    # the not-found path caches ONE reusable manager (the old code leaked
    # an unclosed manager per call)
    assert sum(1 for k in ckpt._MANAGERS if k == os.path.abspath(empty)) == 1
    ckpt.close_manager(empty)
    ckpt.close_manager(d)
    assert os.path.abspath(d) not in ckpt._MANAGERS


def test_save_state_atomic_preserves_previous_on_crash(tmp_path,
                                                       monkeypatch):
    from hydragnn_tpu.train import trainer

    state = _tiny_state()
    fname = trainer.save_state(state, "atomic", str(tmp_path))
    import pickle

    before = pickle.load(open(fname, "rb"))

    from hydragnn_tpu.resilience import ckpt_io

    def exploding_dump(payload, f):
        f.write(b"partial garbage")
        raise OSError("crash mid-write")

    monkeypatch.setattr(ckpt_io.pickle, "dump", exploding_dump)
    state2 = state.replace(step=jnp.asarray(99, jnp.int32))
    with pytest.raises(OSError):
        trainer.save_state(state2, "atomic", str(tmp_path))
    after = pickle.load(open(fname, "rb"))
    assert int(after["step"]) == int(before["step"]) == 3
    # no temp litter
    d = os.path.dirname(fname)
    assert [f for f in os.listdir(d) if ".tmp." in f] == []


def test_same_step_resave_keeps_bundle_valid(tmp_path):
    """A resumed run preempted again before any optimizer step re-saves
    the same step: the existing (identical) checkpoint must be reused,
    never delete-then-rewritten — a failed rewrite would destroy the only
    good copy."""
    from hydragnn_tpu.resilience import save_resume_bundle

    d = str(tmp_path / "resume")
    state = _tiny_state()
    assert save_resume_bundle(state, {"epoch": 1, "items_consumed": 0},
                              d, backoff=0.0)
    # second save at the same step with a checkpoint layer that ALWAYS
    # fails: the state save is skipped entirely (no delete, no write) and
    # only the meta is rewritten, so the bundle stays valid
    assert save_resume_bundle(state, {"epoch": 1, "items_consumed": 0},
                              d, backoff=0.0, chaos=Chaos(ckpt_fails=99),
                              reason="walltime")
    bundle = load_resume_bundle(_tiny_state(), d)
    assert bundle is not None
    _, meta = bundle
    assert meta["reason"] == "walltime" and meta["saved_step"] == 3


def test_preempt_polled_during_resume_replay():
    """A signal arriving while the resumed epoch replays (skips) already-
    consumed items must stop at the SAME position, not wait for the
    replay to finish."""
    from hydragnn_tpu.train.trainer import _run_epoch

    h = PreemptionHandler()
    h.request()
    consumed = {"n": 0}

    class Loader:
        def __iter__(self):
            def gen():
                for _ in range(6):
                    consumed["n"] += 1
                    yield _batch()
            return gen()

    def never_step(state, g):  # pragma: no cover - must not be reached
        raise AssertionError("stepped during replay preemption")

    _run_epoch(never_step, None, Loader(), True, preempt=h, skip_first=4)
    assert h.stop_requested and h.consumed == 4
    assert consumed["n"] == 1  # stopped at the first replayed item


def test_torn_resume_bundle_is_ignored(tmp_path):
    """meta written but state checkpoint missing/mismatched (a save that
    died between the two writes) must fall back, not half-restore."""
    d = str(tmp_path / "resume")
    os.makedirs(d)
    with open(os.path.join(d, "resume_meta.json"), "w") as f:  # graftlint: disable=ROB002 (test fixture in tmp dir; crash durability irrelevant)
        json.dump({"epoch": 1, "items_consumed": 2, "saved_step": 42}, f)
    with pytest.warns(UserWarning, match="inconsistent"):
        assert load_resume_bundle(_tiny_state(), d) is None


def test_config_finalize_writes_resilience_defaults():
    from hydragnn_tpu.config.config import DatasetStats, finalize

    config = {"NeuralNetwork": {
        "Architecture": {"model_type": "SAGE", "hidden_dim": 8,
                         "num_conv_layers": 2, "output_heads": {}},
        "Variables_of_interest": {"type": ["graph"], "output_index": [0],
                                  "output_dim": [1],
                                  "input_node_features": [0]},
        "Training": {"num_epoch": 1, "batch_size": 4},
    }}
    out = finalize(config, DatasetStats(num_nodes_sample=10,
                                        graph_size_variable=False))
    tr = out["NeuralNetwork"]["Training"]
    assert tr["nonfinite_guard"] == 0
    assert tr["preemption"] == 1
    assert tr["guard_max_consecutive"] == 5
    assert tr["ckpt_retries"] == 3
