"""Tier-1 gate for graftlint (hydragnn_tpu/analysis, tools/graftlint.py).

The contract (ISSUE 9, docs/ANALYSIS.md):

- the FULL rule suite over hydragnn_tpu/, tools/ and tests/ reports
  zero unsuppressed, unbaselined findings — a PR that introduces a new
  violation fails here with the rendered finding in the assert message;
- every rule's fixture corpus passes (the analyzer is tested, not just
  its current verdict on the tree);
- seeding a lock-coverage violation into a fixture copy of
  serve/batcher.py is detected (the acceptance probe);
- the knob and health-kind registries are exhaustive against
  grep/AST-extracted ground truth, and docs/KNOBS.md matches the
  generated table;
- suppression, baseline, and diff-scoping mechanics behave.

Keep this module free of undeclared ``HYDRAGNN_*`` string literals and
broad silent excepts — it lints itself.
"""

from __future__ import annotations

import ast
import json
import os
import re
import subprocess
import sys

import pytest

from hydragnn_tpu.analysis import (
    HEALTH_KINDS,
    KNOBS,
    Severity,
    all_rules,
    collect_project,
    emit_knob_docs,
    load_baseline,
    run_project,
)
from hydragnn_tpu.analysis.project import parse_file
from hydragnn_tpu.analysis.runner import BaselineEntry
from hydragnn_tpu.analysis.selftest import run_selftest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
SCAN_PATHS = [os.path.join(REPO, p)
              for p in ("hydragnn_tpu", "tools", "tests")]


# -- the gate ---------------------------------------------------------------

def test_tree_is_clean():
    """THE tier-1 invariant: zero unsuppressed findings over the tree."""
    project = collect_project(REPO, SCAN_PATHS)
    baseline = load_baseline(
        os.path.join(REPO, "tools", "graftlint_baseline.json"))
    result = run_project(project, baseline=baseline)
    rendered = "\n".join(f.render() for f in result.findings)
    assert not result.findings, (
        f"graftlint found {len(result.findings)} new violation(s) — fix "
        f"them, suppress with a justified `# graftlint: disable=RULE "
        f"(reason)`, or (only if provably benign) baseline them:\n"
        f"{rendered}")
    # the baseline must stay free of dead entries
    assert not result.stale_baseline, (
        "stale graftlint baseline entries (the findings are gone): "
        + ", ".join(f"{e.rule}@{e.path}" for e in result.stale_baseline))


def test_baseline_entries_are_justified():
    baseline = load_baseline(
        os.path.join(REPO, "tools", "graftlint_baseline.json"))
    bad = [e for e in baseline
           if not e.justification or e.justification.startswith("TODO")]
    assert not bad, (
        "every baseline entry needs a real one-line justification: "
        + ", ".join(f"{e.rule}@{e.path}" for e in bad))


# -- the analyzer is tested, not just its verdict ---------------------------

def test_rule_fixtures_selftest():
    ok, report = run_selftest()
    assert ok, "rule-fixture selftest failed:\n" + "\n".join(
        line for line in report if line.startswith("FAIL"))


def test_every_rule_has_fixture_coverage():
    """A new rule must ship fixtures (PER_FILE or a special-case harness
    in selftest.py) — adding a rule id without selftest coverage fails."""
    from hydragnn_tpu.analysis.selftest import PER_FILE_RULES, PROJECT_RULES

    covered = set(PER_FILE_RULES) | set(PROJECT_RULES)
    missing = {r.id for r in all_rules()} - covered
    assert not missing, f"rules without selftest coverage: {missing}"


def test_seeded_batcher_lock_violation_detected(tmp_path):
    """Acceptance probe: an unguarded write to a locked class's shared
    attribute seeded into a copy of serve/batcher.py is caught."""
    src = open(os.path.join(
        REPO, "hydragnn_tpu", "serve", "batcher.py")).read()
    anchor = '    def start(self) -> "MicroBatcher":'
    assert anchor in src
    seeded = src.replace(anchor, (
        "    def _seeded_violation(self):\n"
        "        self._fill_sum = 0.0\n\n" + anchor), 1)
    p = tmp_path / "batcher_seeded.py"
    p.write_text(seeded)
    ctx = parse_file(str(p), root=str(tmp_path))
    lck = next(r for r in all_rules() if r.id == "LCK001")
    found = [f for f in lck.check_file(ctx)
             if "_seeded_violation" in f.message]
    assert found, "seeded unguarded write was NOT detected"
    assert "_fill_sum" in found[0].message
    # and the pristine copy stays clean (the seeding is what's detected)
    clean_ctx = parse_file(os.path.join(
        REPO, "hydragnn_tpu", "serve", "batcher.py"), root=REPO)
    assert not list(lck.check_file(clean_ctx))


# -- registry exhaustiveness (acceptance criteria) --------------------------

def _iter_repo_py():
    for top in SCAN_PATHS:
        for dirpath, dirnames, filenames in os.walk(top):
            dirnames[:] = [d for d in dirnames
                           if d not in ("__pycache__", "fixtures")]
            for fn in filenames:
                if fn.endswith(".py"):
                    yield os.path.join(dirpath, fn)


def test_knob_registry_exhaustive():
    """Grep-extracted HYDRAGNN_* names are a subset of the declared
    registry, and every declared knob is documented in docs/KNOBS.md."""
    knob_re = re.compile(r"HYDRAGNN_[A-Z0-9_]+")
    used = set()
    for path in _iter_repo_py():
        if path.endswith(os.path.join("analysis", "registry.py")):
            continue
        for m in knob_re.findall(open(path, encoding="utf-8").read()):
            if not m.endswith("_"):  # prefix constructions are not knobs
                used.add(m)
    undeclared = used - set(KNOBS)
    assert not undeclared, f"undeclared env knobs in code: {undeclared}"
    docs = open(os.path.join(REPO, "docs", "KNOBS.md"),
                encoding="utf-8").read()
    undocumented = {k for k in KNOBS if f"`{k}`" not in docs}
    assert not undocumented, f"knobs missing from docs/KNOBS.md: {undocumented}"


def test_knob_docs_generated_current():
    on_disk = open(os.path.join(REPO, "docs", "KNOBS.md"),
                   encoding="utf-8").read()
    assert on_disk == emit_knob_docs(), (
        "docs/KNOBS.md is stale — regenerate with "
        "`python tools/graftlint.py --emit-docs`")


def test_health_kind_registry_exhaustive():
    """AST-extracted health(kind=...) literals are a subset of the
    declared registry; every declared kind is documented and emitted."""
    emitted = set()
    for path in _iter_repo_py():
        if f"{os.sep}hydragnn_tpu{os.sep}" not in path:
            continue
        tree = ast.parse(open(path, encoding="utf-8").read())
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call) and node.args):
                continue
            fn = node.func
            name = fn.attr if isinstance(fn, ast.Attribute) else (
                fn.id if isinstance(fn, ast.Name) else None)
            if name != "health":
                continue
            a = node.args[0]
            if isinstance(a, ast.Constant) and isinstance(a.value, str):
                emitted.add(a.value)
            elif isinstance(a, ast.IfExp):
                for b in (a.body, a.orelse):
                    if isinstance(b, ast.Constant):
                        emitted.add(b.value)
    undeclared = emitted - set(HEALTH_KINDS)
    assert not undeclared, f"undeclared health kinds: {undeclared}"
    dead = set(HEALTH_KINDS) - emitted
    assert not dead, f"declared-but-never-emitted health kinds: {dead}"
    docs = open(os.path.join(REPO, "docs", "TELEMETRY.md"),
                encoding="utf-8").read()
    undocumented = {k for k in HEALTH_KINDS if f"`{k}`" not in docs}
    assert not undocumented, (
        f"health kinds missing from docs/TELEMETRY.md: {undocumented}")


# -- mechanics --------------------------------------------------------------

_VIOLATING = (
    "import time\n"
    "import jax\n\n\n"
    "@jax.jit\n"
    "def step(x):\n"
    "    return x + time.time()\n"
)


def test_suppression_mechanics(tmp_path):
    p = tmp_path / "v.py"
    p.write_text(_VIOLATING)
    project = collect_project(str(tmp_path), [str(tmp_path)])
    result = run_project(project)
    assert any(f.rule == "TRC001" for f in result.findings)

    p.write_text(_VIOLATING.replace(
        "    return x + time.time()\n",
        "    return x + time.time()  "
        "# graftlint: disable=TRC001 (test)\n"))
    project = collect_project(str(tmp_path), [str(tmp_path)])
    result = run_project(project)
    assert not [f for f in result.findings if f.rule == "TRC001"]
    assert any(f.rule == "TRC001" for f in result.suppressed)


def test_baseline_survives_line_drift(tmp_path):
    p = tmp_path / "v.py"
    p.write_text(_VIOLATING)
    project = collect_project(str(tmp_path), [str(tmp_path)])
    finding = next(f for f in run_project(project).findings
                   if f.rule == "TRC001")
    entry = BaselineEntry(rule=finding.rule, path=finding.path,
                          code=finding.code, justification="test entry")
    # shift the violation down two lines: the entry still matches
    p.write_text("# pad\n# pad\n" + _VIOLATING)
    project = collect_project(str(tmp_path), [str(tmp_path)])
    result = run_project(project, baseline=[entry])
    assert not [f for f in result.findings if f.rule == "TRC001"]
    assert any(f.rule == "TRC001" for f in result.baselined)
    assert not result.stale_baseline


def test_diff_scoping(tmp_path):
    p = tmp_path / "v.py"
    p.write_text(_VIOLATING)
    project = collect_project(str(tmp_path), [str(tmp_path)])
    line = next(f for f in run_project(project).findings
                if f.rule == "TRC001").line
    # finding's line not in the changed set -> scoped out
    scoped = run_project(project, changed={"v.py": {1}})
    assert not scoped.findings
    scoped = run_project(project, changed={"v.py": {line}})
    assert any(f.rule == "TRC001" for f in scoped.findings)


def test_severity_ordering_and_parse():
    assert Severity.parse("error") > Severity.parse("warn") > \
        Severity.parse("note")
    with pytest.raises(ValueError):
        Severity.parse("fatal")


# -- CLI contract -----------------------------------------------------------

def _run_cli(*args, cwd=REPO):
    return subprocess.run(
        [sys.executable, os.path.join(REPO, "tools", "graftlint.py"),
         *args],
        capture_output=True, text=True, cwd=cwd, timeout=120)


def test_cli_exit_codes_and_json(tmp_path):
    clean = tmp_path / "clean.py"
    clean.write_text("X = 1\n")
    r = _run_cli(str(clean))
    assert r.returncode == 0, r.stdout + r.stderr

    dirty = tmp_path / "dirty.py"
    dirty.write_text(_VIOLATING)
    r = _run_cli(str(dirty), "--json")
    assert r.returncode == 1, r.stdout + r.stderr
    doc = json.loads(r.stdout)
    assert doc["counts"]["findings"] >= 1
    assert any(f["rule"] == "TRC001" for f in doc["findings"])
    assert all({"rule", "severity", "path", "line", "message",
                "fingerprint"} <= set(f) for f in doc["findings"])

    r = _run_cli(str(tmp_path / "missing.py"))
    assert r.returncode == 2  # usage error contract

    r = _run_cli("--list-rules")
    assert r.returncode == 0
    for rule in all_rules():
        assert rule.id in r.stdout


def test_cli_loads_without_jax(tmp_path):
    """The CLI's whole point: a lint pass must not pay the jax import
    (dependency-free stdlib ast only)."""
    cli = os.path.join(REPO, "tools", "graftlint.py")
    probe = (
        "import sys\n"
        "sys.argv = ['graftlint', '--list-rules']\n"
        f"g = {{'__name__': '__main__', '__file__': {cli!r}}}\n"
        "try:\n"
        f"    exec(compile(open({cli!r}).read(), {cli!r}, 'exec'), g)\n"
        "except SystemExit as e:\n"
        "    assert (e.code or 0) == 0, e.code\n"
        "assert 'jax' not in sys.modules, 'graftlint imported jax!'\n"
    )
    p = tmp_path / "probe.py"
    p.write_text(probe)
    r = subprocess.run([sys.executable, str(p)], capture_output=True,
                       text=True, timeout=120)
    assert r.returncode == 0, r.stdout + r.stderr
