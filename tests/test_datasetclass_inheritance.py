"""OO dataset path: a user-defined AbstractRawDataset subclass, serialized
through SerializedWriter and read back via SerializedDataset, trains
end-to-end (parity: reference tests/test_datasetclass_inheritance.py:21-60)."""

import json
import os

import numpy as np

from ci_data import generate_cached


def test_datasetclass_inheritance(tmp_path, monkeypatch):
    import jax

    from hydragnn_tpu.config.config import (
        DatasetStats,
        finalize,
        head_specs_from_config,
        label_slices_from_config,
    )
    from hydragnn_tpu.data.dataloader import create_dataloaders
    from hydragnn_tpu.data.pickle_store import (
        SerializedDataset,
        SerializedWriter,
    )
    from hydragnn_tpu.data.raw import LSMSDataset
    from hydragnn_tpu.data.splitting import split_dataset
    from hydragnn_tpu.data.transform import transform_raw_samples
    from hydragnn_tpu.models.base import ModelConfig
    from hydragnn_tpu.models.create import create_model
    from hydragnn_tpu.train.optimizer import select_optimizer
    from hydragnn_tpu.train.trainer import (
        create_train_state,
        train_validate_test,
    )

    with open(os.path.join(os.path.dirname(__file__), "inputs", "ci.json")) as f:
        config = json.load(f)
    config["NeuralNetwork"]["Architecture"]["model_type"] = "GIN"
    config["NeuralNetwork"]["Training"]["num_epoch"] = 3

    # collapse to a single "total" split dir (the reference test also trains
    # on one merged LSMS dir and splits in-process)
    # 400 samples: under the 8-virtual-device CPU mesh the trainer stacks 8
    # micro-batches per step, so the train split must exceed 8 batches
    data_dir = "dataset/ci_inheritance_total"
    config["Dataset"]["path"] = {"total": data_dir}
    generate_cached("inheritance_total", data_dir, 400)

    # user-defined subclass: inherits the LSMS parser, overrides the hook the
    # way downstream projects specialize AbstractRawDataset
    class MyDataset(LSMSDataset):
        loaded = 0

        def transform_file(self, filepath):
            MyDataset.loaded += 1
            return super().transform_file(filepath)

    raw = MyDataset(config)
    raw.load_raw_data()
    assert MyDataset.loaded >= 400
    samples_raw = raw.dataset_list[0]

    # serialize through the generic writer, read back through the dataset
    SerializedWriter(
        samples_raw, str(tmp_path), name="mydataset", label="total",
        minmax_node_feature=raw.minmax_node_feature,
        minmax_graph_feature=raw.minmax_graph_feature)
    reread = SerializedDataset(str(tmp_path), name="mydataset", label="total")
    assert len(reread) == len(samples_raw)
    assert reread.minmax_node_feature is not None

    samples = transform_raw_samples(list(reread), config)
    trainset, valset, testset = split_dataset(
        samples, config["NeuralNetwork"]["Training"]["perc_train"])
    stats = DatasetStats.from_samples(samples, need_deg=False)
    config = finalize(config, stats)
    from hydragnn_tpu.config.config import normalize_output_config

    config = normalize_output_config(config)
    cfg = ModelConfig.from_config(config["NeuralNetwork"])
    model = create_model(cfg)
    hs = head_specs_from_config(config)
    gs, ns = label_slices_from_config(config)
    tl, vl, sl = create_dataloaders(
        trainset, valset, testset, 16, hs,
        graph_feature_slices=gs, node_feature_slices=ns)
    opt = select_optimizer(config["NeuralNetwork"]["Training"]["Optimizer"])
    state = create_train_state(model, next(iter(tl)), opt)
    state, hist = train_validate_test(
        model, cfg, state, opt, tl, vl, sl, config["NeuralNetwork"],
        "ds_inheritance", verbosity=0, logs_dir=str(tmp_path / "logs"))
    assert np.isfinite(hist["train"][-1])
    assert hist["train"][-1] < hist["train"][0], "loss did not decrease"
