"""Run example drivers as subprocesses and assert exit 0 (parity: reference
tests/test_examples.py:18-26, which runs qm9 and md17)."""

import os
import subprocess
import sys

import pytest

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(example, args):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")
    return subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples", example, "train.py"),
         *args],
        cwd=os.path.join(_REPO, "examples", example),
        env=env,
        capture_output=True,
        text=True,
        timeout=540,
    )


@pytest.mark.parametrize("example", ["LennardJones", "qm9", "md17"])
def test_example_runs(example, tmp_path):
    r = _run(example, ["--num_epoch", "3",
                       "--data", str(tmp_path / "data")])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]


def test_lj_preonly_gpack_roundtrip(tmp_path):
    data = str(tmp_path / "data")
    gpack = str(tmp_path / "LJ.gpack")
    r = _run("LennardJones",
             ["--preonly", "--data", data, "--gpack", gpack])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert os.path.exists(gpack + ".p0")
    r = _run("LennardJones",
             ["--use_gpack", "--gpack", gpack, "--data", data,
              "--num_epoch", "2"])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
