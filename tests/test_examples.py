"""Run example drivers as subprocesses and assert exit 0 (parity: reference
tests/test_examples.py:18-26, which runs qm9 and md17)."""

import os
import subprocess
import sys

import pytest

# Each case subprocess-trains a full example for several epochs (~1-5 min
# on CPU).  Until the shard_map import fix these failed at import time and
# cost tier-1 nothing; actually RUNNING them does not fit the 870 s tier-1
# budget, so they are tier-2 (run with `-m slow` or no marker filter).
pytestmark = pytest.mark.slow

_REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _run(example, args):
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.setdefault("XLA_FLAGS", "--xla_force_host_platform_device_count=1")
    return subprocess.run(
        [sys.executable, os.path.join(_REPO, "examples", example, "train.py"),
         *args],
        cwd=os.path.join(_REPO, "examples", example),
        env=env,
        capture_output=True,
        text=True,
        timeout=540,
    )


@pytest.mark.parametrize("example", ["LennardJones", "qm9", "md17"])
def test_example_runs(example, tmp_path):
    r = _run(example, ["--num_epoch", "3",
                       "--data", str(tmp_path / "data")])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]


def test_csce_gap_runs(tmp_path):
    """SMILES-CSV gap driver (reference examples/csce/train_gap.py):
    synthesizes the CSV at --datafile when missing, then trains on it."""
    csv_path = str(tmp_path / "csce.csv")
    r = subprocess.run(
        [sys.executable,
         os.path.join(_REPO, "examples", "csce", "train_gap.py"),
         "--num_epoch", "3", "--num_mols", "80", "--datafile", csv_path],
        cwd=str(tmp_path),
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=540,
    )
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert os.path.exists(csv_path)


def test_lsms_runs(tmp_path):
    """LSMS config-driven driver through plain run_training.  cwd=tmp_path so
    logs/ and serialized_dataset/ artifacts never land in the source tree."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env["SERIALIZED_DATA_PATH"] = str(tmp_path)
    r = subprocess.run(
        [sys.executable,
         os.path.join(_REPO, "examples", "lsms", "train.py"),
         "--num_epoch", "3", "--num_configs", "80",
         "--data", str(tmp_path / "data")],
        cwd=str(tmp_path), env=env,
        capture_output=True, text=True, timeout=540,
    )
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]


def test_ogb_gap_runs(tmp_path):
    """OGB SMILES-gap variant of the csce driver."""
    r = subprocess.run(
        [sys.executable,
         os.path.join(_REPO, "examples", "ogb", "train_gap.py"),
         "--num_epoch", "2", "--num_mols", "60",
         "--datafile", str(tmp_path / "ogb.csv")],
        cwd=str(tmp_path),
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=540,
    )
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]


def test_hpo_multi_async_runs(tmp_path):
    """Async multi-job HPO driver: 2 concurrent subprocess trials."""
    r = subprocess.run(
        [sys.executable,
         os.path.join(_REPO, "examples", "multidataset_hpo", "hpo_multi.py"),
         "--n_trials", "2", "--n_concurrent", "2",
         "--num_epoch", "2", "--num_mols", "50"],
        cwd=str(tmp_path),
        env={**os.environ, "JAX_PLATFORMS": "cpu"},
        capture_output=True, text=True, timeout=540,
    )
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert "BEST val loss" in r.stdout


def test_mptrj_runs(tmp_path):
    """MPTrj-style trajectories: energy+forces multitask with PNA."""
    r = _run("mptrj", ["--num_epoch", "2", "--num_traj", "10"])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]


def test_dftb_uv_spectrum_runs(tmp_path):
    """Wide-head (1000-dim spectrum) decoder stress (reference
    examples/dftb_uv_spectrum/train_smooth_uv_spectrum.py)."""
    r = _run("dftb_uv_spectrum",
             ["--num_epoch", "2", "--num_mols", "60"])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]


def test_open_catalyst_runs(tmp_path):
    """OC20-IS2RE-style driver (BASELINE scale config: OC20 + DimeNet)."""
    r = _run("open_catalyst_2020",
             ["--num_epoch", "2", "--num_frames", "40"])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]


def test_open_catalyst_preonly_gpack(tmp_path):
    gpack = str(tmp_path / "oc.gpack")
    r = _run("open_catalyst_2020",
             ["--preonly", "--gpack", gpack, "--num_frames", "30"])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert os.path.exists(gpack + ".p0")
    r = _run("open_catalyst_2020",
             ["--use_gpack", "--gpack", gpack, "--num_epoch", "2"])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]


def test_lj_preonly_gpack_roundtrip(tmp_path):
    data = str(tmp_path / "data")
    gpack = str(tmp_path / "LJ.gpack")
    r = _run("LennardJones",
             ["--preonly", "--data", data, "--gpack", gpack])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
    assert os.path.exists(gpack + ".p0")
    r = _run("LennardJones",
             ["--use_gpack", "--gpack", gpack, "--data", data,
              "--num_epoch", "2"])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]


@pytest.mark.parametrize("example,extra", [
    ("ising_model", ["--num_configs", "60"]),
    ("eam", ["--num_configs", "50"]),
    ("qm7x", []),
    ("ani1_x", []),
    ("alexandria", ["--num_configs", "40"]),
    ("open_catalyst_2022", ["--num_frames", "30"]),
])
def test_more_example_dirs(example, extra, tmp_path):
    """Breadth coverage of the remaining reference example dirs."""
    r = _run(example, ["--num_epoch", "2", *extra])
    assert r.returncode == 0, r.stdout[-2000:] + r.stderr[-2000:]
