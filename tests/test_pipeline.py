"""Input-pipeline dispatch optimizations: scan-chunked train steps,
device prefetch, and the device-resident dataset mode (docs/PERF.md)."""

import numpy as np
import jax
import jax.numpy as jnp

from hydragnn_tpu.data.prefetch import DevicePrefetcher, ResidentDeviceLoader
from hydragnn_tpu.graph.batch import GraphSample, HeadSpec, PadSpec, collate
from hydragnn_tpu.graph.neighborlist import radius_graph
from hydragnn_tpu.models.base import GraphHeadCfg, ModelConfig
from hydragnn_tpu.models.create import create_model
from hydragnn_tpu.parallel.mesh import DeviceStackLoader, stack_batches
from hydragnn_tpu.train.optimizer import select_optimizer
from hydragnn_tpu.train.trainer import (
    create_train_state,
    make_scan_train_step,
    make_train_step,
)


def _batches(n, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n):
        samples = []
        for _ in range(8):
            pos = rng.rand(10, 3).astype(np.float32) * 2.0
            x = rng.rand(10, 1).astype(np.float32)
            ei = radius_graph(pos, 1.2, 10)
            samples.append(GraphSample(
                x=x, pos=pos, edge_index=ei,
                graph_y=x.sum(keepdims=True)[0], node_y=x))
        pad = PadSpec.for_batch(8, 10, 90)
        out.append(collate(samples, pad, [HeadSpec("e", "graph", 1)]))
    return out


def _model():
    cfg = ModelConfig(
        model_type="SAGE", input_dim=1, hidden_dim=8, output_dim=(1,),
        output_type=("graph",), graph_head=GraphHeadCfg(1, 8, 1, (8,)),
        node_head=None, task_weights=(1.0,), num_conv_layers=2)
    return cfg, create_model(cfg)


def test_scan_step_equals_sequential():
    """K steps under lax.scan must match K sequential jit dispatches, in
    both final params and graph-weighted metrics."""
    batches = _batches(4)
    cfg, model = _model()
    opt = select_optimizer({"type": "AdamW", "learning_rate": 1e-3})
    s0 = create_train_state(model, batches[0], opt)

    step = jax.jit(make_train_step(model, cfg, opt))
    s_seq, tot, n = s0, 0.0, 0.0
    for b in batches:
        s_seq, m = step(s_seq, b)
        tot += float(m["loss"]) * float(m["num_graphs"])
        n += float(m["num_graphs"])

    scan = jax.jit(make_scan_train_step(model, cfg, opt, None, 4))
    s_scan, ms = scan(s0, stack_batches(batches))

    for a, b_ in zip(jax.tree_util.tree_leaves(s_seq.params),
                     jax.tree_util.tree_leaves(s_scan.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-5, atol=2e-5)
    assert abs(tot / n - float(ms["loss"])) < 1e-5
    assert float(ms["num_graphs"]) == n


def test_device_prefetcher_passthrough():
    """DevicePrefetcher yields the same batches (as device arrays), in
    order, and re-raises producer errors."""
    batches = _batches(3)
    got = list(DevicePrefetcher(batches))
    assert len(got) == 3
    for a, b in zip(got, batches):
        np.testing.assert_array_equal(np.asarray(a.x), b.x)

    class Boom:
        def __iter__(self):
            yield batches[0]
            raise RuntimeError("producer died")

    import pytest

    with pytest.raises(RuntimeError, match="producer died"):
        list(DevicePrefetcher(Boom()))


def test_resident_loader_caches_and_permutes():
    batches = _batches(5)
    ld = ResidentDeviceLoader(batches, seed=7)
    ld.set_epoch(0)
    first = list(ld)
    assert len(first) == 5

    def key(b):
        return float(np.asarray(b.x).sum())

    base = [key(b) for b in first]
    ld.set_epoch(1)
    second = [key(b) for b in ld]
    # same multiset of batches, epoch-dependent order
    assert sorted(second) == sorted(base)
    ld.set_epoch(2)
    third = [key(b) for b in ld]
    assert sorted(third) == sorted(base)
    assert second != third or second != base  # permutation actually varies


def test_dp_scan_step_matches_sequential():
    """Mesh-path scan (steps=2 over [K, D, ...] superbatches) must equal two
    sequential DP dispatches."""
    from hydragnn_tpu.parallel.mesh import (
        make_dp_train_step,
        make_mesh,
        replicate_state,
    )

    n_dev = len(jax.devices())
    mesh = make_mesh()
    cfg, model = _model()
    opt = select_optimizer({"type": "AdamW", "learning_rate": 1e-3})
    batches = _batches(2 * n_dev, seed=2)
    state = create_train_state(model, batches[0], opt)

    stacked = [stack_batches(batches[i * n_dev:(i + 1) * n_dev])
               for i in range(2)]

    s_seq = replicate_state(state, mesh)
    step = make_dp_train_step(model, cfg, opt, mesh)
    for sb in stacked:
        s_seq, m = step(s_seq, sb)

    s_scan = replicate_state(state, mesh)
    scan_step = make_dp_train_step(model, cfg, opt, mesh, steps=2)
    superbatch = stack_batches(stacked)  # [K, D, ...]
    s_scan, ms = scan_step(s_scan, superbatch)

    for a, b_ in zip(jax.tree_util.tree_leaves(s_seq.params),
                     jax.tree_util.tree_leaves(s_scan.params)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b_),
                                   rtol=2e-5, atol=2e-5)


def test_align_bucket_group():
    from hydragnn_tpu.data.dataloader import GraphDataLoader
    from hydragnn_tpu.train.trainer import _align_bucket_group

    rng = np.random.RandomState(0)
    samples = []
    for _ in range(64):
        n = int(rng.randint(4, 11))
        pos = rng.rand(n, 3).astype(np.float32) * 2.0
        x = rng.rand(n, 1).astype(np.float32)
        ei = radius_graph(pos, 1.2, 10)
        samples.append(GraphSample(x=x, pos=pos, edge_index=ei,
                                   graph_y=x.sum(keepdims=True)[0], node_y=x))
    from hydragnn_tpu.data.dataloader import bucket_pad_specs

    pads = bucket_pad_specs(samples, 8, 3)
    ld = GraphDataLoader(samples, [HeadSpec("e", "graph", 1)], 8,
                         pad_specs=pads, bucket_group=1, shuffle=True)
    # wrapped behind prefetch-style .loader chains, alignment still lands
    class Wrap:
        def __init__(self, loader):
            self.loader = loader

    _align_bucket_group(Wrap(ld), 4)
    assert ld.bucket_group == 4
    # stacking 4 consecutive batches now never mixes bucket shapes
    from hydragnn_tpu.parallel.mesh import DeviceStackLoader

    stacked = list(DeviceStackLoader(ld, 4, drop_last=True))
    assert stacked, "no stacked batches produced"


def test_resident_loader_partial_epochs_keep_staged_work():
    """An abandoned staging epoch (MAX_NUM_BATCH-style early break) must not
    discard staged batches: the next epoch replays the staged prefix and
    staging continues where it stopped."""
    batches = _batches(5, seed=4)
    pulls = {"n": 0}

    class Counting:
        def __iter__(self):
            for b in batches:
                pulls["n"] += 1
                yield b

        def __len__(self):
            return len(batches)

    ld = ResidentDeviceLoader(Counting(), seed=3)
    ld.set_epoch(0)
    it = iter(ld)
    got0 = [next(it) for _ in range(2)]
    it.close()
    assert pulls["n"] == 2

    # next epoch: UNSTAGED batches come first (a capped consumer keeps
    # advancing staging), then the staged prefix replays — still one full
    # epoch, with only 3 more pulls from the source
    ld.set_epoch(1)
    got1 = list(ld)
    assert len(got1) == 5
    assert pulls["n"] == 5
    np.testing.assert_array_equal(np.asarray(got1[0].x), batches[2].x)
    np.testing.assert_array_equal(np.asarray(got1[-2].x), np.asarray(got0[0].x))

    ld.set_epoch(2)
    assert len(list(ld)) == 5
    assert pulls["n"] == 5  # fully cached now

    # capped consumption advances coverage epoch over epoch (no frozen
    # prefix): a fresh loader pulled 2-at-a-time sees batches 0,1 then 2,3
    ld2 = ResidentDeviceLoader(Counting(), seed=3)
    def take2(epoch):
        ld2.set_epoch(epoch)
        it2 = iter(ld2)
        out = [next(it2), next(it2)]
        it2.close()
        return out
    pulls["n"] = 0
    a = take2(0)
    b = take2(1)
    np.testing.assert_array_equal(np.asarray(b[0].x), batches[2].x)


def test_max_num_batch_counts_steps_not_dispatches(monkeypatch):
    """HYDRAGNN_MAX_NUM_BATCH=2 with steps_per_item=2 must stop after ONE
    scanned dispatch (2 steps), keeping K=1 and K=8 runs comparable."""
    from hydragnn_tpu.train.trainer import _run_epoch

    batches = _batches(4, seed=5)
    cfg, model = _model()
    opt = select_optimizer({"type": "AdamW", "learning_rate": 1e-3})
    state = create_train_state(model, batches[0], opt)
    scan = jax.jit(make_scan_train_step(model, cfg, opt, None, 2))
    supers = [stack_batches(batches[:2]), stack_batches(batches[2:])]

    calls = {"n": 0}

    def counting_step(s, g):
        calls["n"] += 1
        return scan(s, g)

    monkeypatch.setenv("HYDRAGNN_MAX_NUM_BATCH", "2")
    _run_epoch(counting_step, state, supers, True, steps_per_item=2)
    assert calls["n"] == 1


def test_trainer_env_knobs_smoke(monkeypatch, tmp_path):
    """HYDRAGNN_STEPS_PER_DISPATCH + HYDRAGNN_RESIDENT_DATASET drive a short
    training through train_validate_test and still converge."""
    from hydragnn_tpu.train.trainer import train_validate_test

    monkeypatch.setenv("HYDRAGNN_STEPS_PER_DISPATCH", "2")
    monkeypatch.setenv("HYDRAGNN_RESIDENT_DATASET", "1")
    batches = _batches(4, seed=1)

    class ListLoader:
        def __init__(self, bs):
            self.bs = list(bs)

        def set_epoch(self, e):
            pass

        def __len__(self):
            return len(self.bs)

        def __iter__(self):
            return iter(self.bs)

    cfg, model = _model()
    opt = select_optimizer({"type": "AdamW", "learning_rate": 0.01})
    state = create_train_state(model, batches[0], opt)
    state, hist = train_validate_test(
        model, cfg, state, opt,
        ListLoader(batches), ListLoader(batches[:1]), ListLoader(batches[:1]),
        {"Training": {"num_epoch": 8},
         "Variables_of_interest": {"output_names": ["e"]}},
        log_name="pipeline_smoke", logs_dir=str(tmp_path),
        use_mesh_dp=False,
    )
    losses = hist["train"]
    assert losses[-1] < losses[0] * 0.7, losses


def test_trainer_mesh_knobs_smoke(monkeypatch, tmp_path):
    """Same knobs through the MESH path (8-device CPU): scan superbatches +
    resident staging with mesh sharding must still converge."""
    from hydragnn_tpu.train.trainer import train_validate_test

    n_dev = len(jax.devices())
    monkeypatch.setenv("HYDRAGNN_STEPS_PER_DISPATCH", "2")
    monkeypatch.setenv("HYDRAGNN_RESIDENT_DATASET", "1")
    batches = _batches(4 * n_dev, seed=3)

    class ListLoader:
        def __init__(self, bs):
            self.bs = list(bs)

        def set_epoch(self, e):
            pass

        def __len__(self):
            return len(self.bs)

        def __iter__(self):
            return iter(self.bs)

    cfg, model = _model()
    opt = select_optimizer({"type": "AdamW", "learning_rate": 0.01})
    state = create_train_state(model, batches[0], opt)
    state, hist = train_validate_test(
        model, cfg, state, opt,
        ListLoader(batches), ListLoader(batches[:n_dev]),
        ListLoader(batches[:n_dev]),
        {"Training": {"num_epoch": 8},
         "Variables_of_interest": {"output_names": ["e"]}},
        log_name="pipeline_mesh_smoke", logs_dir=str(tmp_path),
        use_mesh_dp=True,
    )
    losses = hist["train"]
    assert losses[-1] < losses[0] * 0.7, losses


def test_process_collate_matches_sequential():
    """ProcessPrefetchLoader (forked collate workers) yields batch-for-batch
    the same arrays, in the same order, as the plain loader; a second epoch
    (reused pool) reshuffles identically to the sequential loader."""
    import numpy as np

    from hydragnn_tpu.data.dataloader import GraphDataLoader
    from hydragnn_tpu.data.prefetch import ProcessPrefetchLoader
    from hydragnn_tpu.graph.batch import GraphSample, HeadSpec
    from hydragnn_tpu.graph.neighborlist import radius_graph

    rng = np.random.RandomState(0)
    samples = []
    for _ in range(40):
        pos = rng.rand(7, 3).astype(np.float32) * 2
        samples.append(GraphSample(
            x=rng.rand(7, 2).astype(np.float32), pos=pos,
            edge_index=radius_graph(pos, 1.3, 8),
            graph_y=rng.rand(1).astype(np.float32)))
    heads = [HeadSpec("e", "graph", 1)]

    def mk():
        return GraphDataLoader(samples, heads, 8, shuffle=True, seed=3)

    plain = mk()
    proc = ProcessPrefetchLoader(mk(), num_workers=2)
    try:
        for epoch in (0, 1):
            plain.set_epoch(epoch)
            proc.set_epoch(epoch)
            got = list(proc)
            want = list(plain)
            assert len(got) == len(want) == len(plain)
            for a, b in zip(got, want):
                for la, lb in zip(jax.tree_util.tree_leaves(a),
                                  jax.tree_util.tree_leaves(b)):
                    np.testing.assert_array_equal(
                        np.asarray(la), np.asarray(lb))
    finally:
        proc.close()
