"""Fused gather-multiply-segment-sum kernel (ops/fused_mp.py): exactness
against the XLA path, gradients, extreme degree distributions (the dense
schedule has no degree bound), and the model-level
HYDRAGNN_AGGR_BACKEND=fused dispatch."""

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from hydragnn_tpu.graph.batch import GraphSample, HeadSpec, PadSpec, collate
from hydragnn_tpu.graph.neighborlist import radius_graph
from hydragnn_tpu.ops.fused_mp import gather_mul_segment_sum


def _batch(n_graphs=24, max_nodes=16, seed=0, max_neigh=10):
    rng = np.random.RandomState(seed)
    samples = []
    for _ in range(n_graphs):
        n = int(rng.randint(3, max_nodes + 1))
        pos = rng.rand(n, 3).astype(np.float32) * 2.5
        x = rng.rand(n, 2).astype(np.float32)
        ei = radius_graph(pos, 1.4, max_neigh)
        samples.append(GraphSample(x=x, pos=pos, edge_index=ei,
                                   graph_y=np.ones(1, np.float32), node_y=x))
    pad = PadSpec.for_batch(n_graphs, max_nodes, max_nodes * max_neigh)
    return collate(samples, pad, [HeadSpec("e", "graph", 1)])


def _arrays(b, f=64, seed=1):
    rng = np.random.RandomState(seed)
    n, e = b.x.shape[0], b.senders.shape[0]
    x = jnp.asarray(rng.rand(n, f), jnp.float32)
    w = jnp.asarray(rng.rand(e, f), jnp.float32) * jnp.asarray(
        b.edge_mask)[:, None]
    perm = jnp.asarray(np.argsort(np.asarray(b.senders), kind="stable"),
                       jnp.int32)
    return x, w, perm


def _ref(b, x, w):
    return jax.ops.segment_sum(
        x[jnp.asarray(b.senders)] * w, jnp.asarray(b.receivers),
        num_segments=x.shape[0])


def test_fused_forward_exact():
    b = _batch()
    x, w, perm = _arrays(b)
    out = gather_mul_segment_sum(
        x, w, jnp.asarray(b.senders), jnp.asarray(b.receivers), perm)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(b, x, w)),
                               rtol=1e-5, atol=1e-5)


def test_fused_gradients_exact():
    b = _batch(seed=2)
    x, w, perm = _arrays(b, seed=3)
    s, r = jnp.asarray(b.senders), jnp.asarray(b.receivers)

    gx1, gw1 = jax.grad(
        lambda x_, w_: jnp.sum(
            gather_mul_segment_sum(x_, w_, s, r, perm) ** 2),
        argnums=(0, 1))(x, w)
    gx2, gw2 = jax.grad(
        lambda x_, w_: jnp.sum(_ref(b, x_, w_) ** 2), argnums=(0, 1))(x, w)
    np.testing.assert_allclose(np.asarray(gx1), np.asarray(gx2),
                               rtol=1e-5, atol=1e-5)
    m = np.asarray(b.edge_mask)[:, None]
    np.testing.assert_allclose(np.asarray(gw1) * m, np.asarray(gw2) * m,
                               rtol=1e-5, atol=1e-5)


def test_extreme_degrees_exact():
    """The dense schedule has no degree bound: dense all-to-all graphs
    (degree 15 in a 16-node graph) are processed exactly, fwd and bwd."""
    rng = np.random.RandomState(0)
    samples = []
    for _ in range(24):
        n = 16
        pos = rng.rand(n, 3).astype(np.float32)  # dense: everyone in range
        x = rng.rand(n, 2).astype(np.float32)
        ei = radius_graph(pos, 10.0, 15)
        samples.append(GraphSample(x=x, pos=pos, edge_index=ei,
                                   graph_y=np.ones(1, np.float32), node_y=x))
    pad = PadSpec.for_batch(24, 16, 16 * 15)
    b = collate(samples, pad, [HeadSpec("e", "graph", 1)])
    x, w, perm = _arrays(b)
    s, r = jnp.asarray(b.senders), jnp.asarray(b.receivers)
    out = gather_mul_segment_sum(x, w, s, r, perm)
    np.testing.assert_allclose(np.asarray(out), np.asarray(_ref(b, x, w)),
                               rtol=1e-5, atol=1e-5)
    gx1 = jax.grad(lambda x_: jnp.sum(
        gather_mul_segment_sum(x_, w, s, r, perm) ** 2))(x)
    gx2 = jax.grad(lambda x_: jnp.sum(_ref(b, x_, w) ** 2))(x)
    np.testing.assert_allclose(np.asarray(gx1), np.asarray(gx2),
                               rtol=1e-5, atol=1e-5)


def test_collate_attaches_perm_under_fused_backend(monkeypatch):
    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", "fused")
    b = _batch()
    assert "edge_perm_sender" in b.extras
    perm = np.asarray(b.extras["edge_perm_sender"])
    s = np.asarray(b.senders)
    assert (np.diff(s[perm]) >= 0).all()
    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", "scatter")
    b2 = _batch()
    assert "edge_perm_sender" not in (b2.extras or {})


def test_collate_skips_perm_when_invariants_broken(monkeypatch):
    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", "fused")
    rng = np.random.RandomState(0)

    # graph larger than the kernel's node block -> no perm
    n = 200
    pos = rng.rand(n, 3).astype(np.float32) * 6.0
    x = rng.rand(n, 2).astype(np.float32)
    ei = radius_graph(pos, 1.4, 10)
    big = GraphSample(x=x, pos=pos, edge_index=ei,
                      graph_y=np.ones(1, np.float32), node_y=x)
    pad = PadSpec.for_batch(1, n, n * 10)
    b = collate([big], pad, [HeadSpec("e", "graph", 1)])
    assert "edge_perm_sender" not in (b.extras or {})

    # receiver-unsorted stored edge list (external pipeline) -> no perm
    n2 = 8
    pos2 = rng.rand(n2, 3).astype(np.float32)
    x2 = rng.rand(n2, 2).astype(np.float32)
    ei2 = np.asarray([[1, 0, 3], [5, 2, 0]], np.int32)  # recv not sorted
    small = GraphSample(x=x2, pos=pos2, edge_index=ei2,
                        graph_y=np.ones(1, np.float32), node_y=x2)
    pad2 = PadSpec.for_batch(1, n2, 8)
    b2 = collate([small], pad2, [HeadSpec("e", "graph", 1)])
    assert "edge_perm_sender" not in (b2.extras or {})


def test_gather_segment_sum_wless_exact():
    """The w-less variant (GIN/MFC neighbor sum) and its gradient."""
    from hydragnn_tpu.ops.fused_mp import gather_segment_sum

    b = _batch(seed=7)
    x, _, perm = _arrays(b, seed=8)
    s, r = jnp.asarray(b.senders), jnp.asarray(b.receivers)
    mask = jnp.asarray(b.edge_mask)

    out = gather_segment_sum(x, s, r, perm, mask)
    want = jax.ops.segment_sum(
        x[s] * mask[:, None], r, num_segments=x.shape[0])
    np.testing.assert_allclose(np.asarray(out), np.asarray(want),
                               rtol=1e-5, atol=1e-5)

    g1 = jax.grad(lambda x_: jnp.sum(
        gather_segment_sum(x_, s, r, perm, mask) ** 2))(x)
    g2 = jax.grad(lambda x_: jnp.sum(jax.ops.segment_sum(
        x_[s] * mask[:, None], r, num_segments=x.shape[0]) ** 2))(x)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-5, atol=1e-5)


def test_segment_sum_dense_exact():
    """Scatter-only dense-schedule kernel vs jax.ops.segment_sum, fwd+bwd,
    over both sorted id streams the models use (receivers, node_gid)."""
    from hydragnn_tpu.ops.fused_mp import segment_sum_dense

    b = _batch(seed=11)
    rng = np.random.RandomState(12)
    e = b.senders.shape[0]
    data = jnp.asarray(rng.rand(e, 48), jnp.float32) * jnp.asarray(
        b.edge_mask)[:, None]
    r = jnp.asarray(b.receivers)
    n = b.x.shape[0]
    np.testing.assert_allclose(
        np.asarray(segment_sum_dense(data, r, n)),
        np.asarray(jax.ops.segment_sum(data, r, num_segments=n)),
        rtol=1e-5, atol=1e-5)
    g1 = jax.grad(lambda d: jnp.sum(segment_sum_dense(d, r, n) ** 2))(data)
    g2 = jax.grad(lambda d: jnp.sum(
        jax.ops.segment_sum(d, r, num_segments=n) ** 2))(data)
    np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                               rtol=1e-5, atol=1e-5)

    nd = jnp.asarray(rng.rand(n, 32), jnp.float32)
    gid = jnp.asarray(b.node_gid)
    ng = b.graph_mask.shape[0]
    np.testing.assert_allclose(
        np.asarray(segment_sum_dense(nd, gid, ng)),
        np.asarray(jax.ops.segment_sum(nd, gid, num_segments=ng)),
        rtol=1e-5, atol=1e-5)


from hydragnn_tpu.models.create import ALL_ARCHS

# the canonical arch list (shared with bench.py's sweep) minus the two
# stacks with dedicated parity tests below — a newly registered arch lands
# in THIS parametrization (and the bench sweep) automatically
_PARITY_ARCHS = [a for a in ALL_ARCHS if a not in ("SchNet", "DimeNet")]


@pytest.mark.parametrize("model_type", _PARITY_ARCHS)
def test_sum_aggr_models_fused_match_scatter(model_type, monkeypatch):
    from hydragnn_tpu.models.base import GraphHeadCfg, ModelConfig
    from hydragnn_tpu.models.create import create_model

    cfg = ModelConfig(
        model_type=model_type, input_dim=1,
        # CGCNN's conv is dim-preserving: hidden_dim forced = input_dim
        hidden_dim=1 if model_type == "CGCNN" else 16,
        output_dim=(1,),
        output_type=("graph",), graph_head=GraphHeadCfg(1, 16, 1, (16,)),
        node_head=None, task_weights=(1.0,), num_conv_layers=2,
        max_degree=16, max_neighbours=16,
        pna_avg_deg_log=1.1, pna_avg_deg_lin=3.0)
    model = create_model(cfg)

    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", "fused")
    b_fused = _batch(seed=9)
    assert "edge_perm_sender" in b_fused.extras
    v = model.init({"params": jax.random.PRNGKey(0),
                    "dropout": jax.random.PRNGKey(1)}, b_fused, train=False)

    def loss(params, b):
        out = model.apply({"params": params,
                           "batch_stats": v.get("batch_stats", {})},
                          b, train=False)
        return jnp.sum(out[0] ** 2)

    lf = float(loss(v["params"], b_fused))
    gf = jax.grad(loss)(v["params"], b_fused)

    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", "scatter")
    b_plain = _batch(seed=9)
    lp = float(loss(v["params"], b_plain))
    gp = jax.grad(loss)(v["params"], b_plain)

    assert abs(lf - lp) < 1e-4 * max(1.0, abs(lp))
    for a, c in zip(jax.tree_util.tree_leaves(gf),
                    jax.tree_util.tree_leaves(gp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=1e-4, atol=1e-5)


def test_dimenet_model_fused_matches_scatter(monkeypatch):
    """DimeNet's triplet and output aggregations ride the dense sorted
    scatter under the fused backend; numerics must match exactly."""
    from hydragnn_tpu.models.base import GraphHeadCfg, ModelConfig
    from hydragnn_tpu.models.create import create_model
    from hydragnn_tpu.models.dimenet import add_dimenet_extras

    cfg = ModelConfig(
        model_type="DimeNet", input_dim=1, hidden_dim=8, output_dim=(1,),
        output_type=("graph",), graph_head=GraphHeadCfg(1, 8, 1, (8,)),
        node_head=None, task_weights=(1.0,), num_conv_layers=2,
        basis_emb_size=4, envelope_exponent=5, int_emb_size=4,
        out_emb_size=4, num_after_skip=1, num_before_skip=1, num_radial=4,
        num_spherical=3, radius=1.4, max_neighbours=10)
    model = create_model(cfg)

    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", "fused")
    b_fused = add_dimenet_extras(_batch(seed=13), max_triplets=4096)
    assert "edge_perm_sender" in b_fused.extras
    v = model.init({"params": jax.random.PRNGKey(0),
                    "dropout": jax.random.PRNGKey(1)}, b_fused, train=False)

    def loss(params, b):
        out = model.apply({"params": params, "batch_stats": {}},
                          b, train=False)
        return jnp.sum(out[0] ** 2)

    lf = float(loss(v["params"], b_fused))
    gf = jax.grad(loss)(v["params"], b_fused)

    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", "scatter")
    b_plain = add_dimenet_extras(_batch(seed=13), max_triplets=4096)
    lp = float(loss(v["params"], b_plain))
    gp = jax.grad(loss)(v["params"], b_plain)

    assert abs(lf - lp) < 1e-4 * max(1.0, abs(lp))
    for a, c in zip(jax.tree_util.tree_leaves(gf),
                    jax.tree_util.tree_leaves(gp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=1e-4, atol=1e-5)


def test_schnet_model_fused_matches_scatter(monkeypatch):
    """Full SchNet forward + grads must be identical under the fused
    backend (the kernel is exact, not approximate)."""
    from hydragnn_tpu.models.base import GraphHeadCfg, ModelConfig
    from hydragnn_tpu.models.create import create_model

    cfg = ModelConfig(
        model_type="SchNet", input_dim=1, hidden_dim=16, output_dim=(1,),
        output_type=("graph",), graph_head=GraphHeadCfg(1, 16, 1, (16,)),
        node_head=None, task_weights=(1.0,), num_conv_layers=2,
        num_gaussians=8, num_filters=16, radius=1.4, max_neighbours=10)
    model = create_model(cfg)

    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", "fused")
    b_fused = _batch(seed=5)
    assert "edge_perm_sender" in b_fused.extras
    v = model.init({"params": jax.random.PRNGKey(0),
                    "dropout": jax.random.PRNGKey(1)}, b_fused, train=False)

    def loss_fused(params):
        out = model.apply({"params": params, "batch_stats": {}},
                          b_fused, train=False)
        return jnp.sum(out[0] ** 2)

    lf = float(loss_fused(v["params"]))
    gf = jax.grad(loss_fused)(v["params"])

    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", "scatter")
    b_plain = _batch(seed=5)

    def loss_plain(params):
        out = model.apply({"params": params, "batch_stats": {}},
                          b_plain, train=False)
        return jnp.sum(out[0] ** 2)

    lp = float(loss_plain(v["params"]))
    gp = jax.grad(loss_plain)(v["params"])

    assert abs(lf - lp) < 1e-4 * max(1.0, abs(lp))
    for a, c in zip(jax.tree_util.tree_leaves(gf),
                    jax.tree_util.tree_leaves(gp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=1e-4, atol=1e-5)


def test_dense_bwd_gathers_exact(monkeypatch):
    """gather_sender / gather_receiver_sorted: forward identical to plain
    gathers, backward (dense-scatter path) identical to XLA's."""
    from hydragnn_tpu.graph import segment

    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", "fused")
    b = _batch(seed=13)
    rng = np.random.RandomState(14)
    x = jnp.asarray(rng.rand(b.x.shape[0], 32), jnp.float32)

    for fn, idx in ((segment.gather_sender, b.senders),
                    (segment.gather_receiver_sorted, b.receivers)):
        np.testing.assert_array_equal(
            np.asarray(fn(x, b)), np.asarray(x[jnp.asarray(idx)]))
        g1 = jax.grad(lambda x_: jnp.sum(fn(x_, b) ** 2))(x)
        g2 = jax.grad(lambda x_: jnp.sum(x_[jnp.asarray(idx)] ** 2))(x)
        # f32 accumulation order differs between the onehot-matmul scatter
        # and XLA's scatter-add; values here reach ~1e4
        np.testing.assert_allclose(np.asarray(g1), np.asarray(g2),
                                   rtol=1e-4, atol=1e-5)


def test_dimenet_fused_triplet_parity(monkeypatch):
    """The edge-space fused triplet interaction (tri_window > 0, W-window
    gather_mul_segment_sum) must match the composed gather+scatter path in
    forward AND param gradients on a real collated DimeNet batch."""
    monkeypatch.setenv("HYDRAGNN_AGGR_BACKEND", "fused")
    monkeypatch.setenv("HYDRAGNN_DIMENET_FUSED_TRI", "1")
    from hydragnn_tpu.graph.batch import (
        GraphSample, HeadSpec, PadSpec, collate)
    from hydragnn_tpu.graph.neighborlist import radius_graph
    from hydragnn_tpu.models.base import GraphHeadCfg, ModelConfig
    from hydragnn_tpu.models.create import create_model
    from hydragnn_tpu.models.dimenet import (
        add_dimenet_extras, count_triplets)

    rng = np.random.RandomState(0)
    samples = []
    for _ in range(5):
        pos = rng.rand(8, 3).astype(np.float32) * 2.0
        samples.append(GraphSample(
            x=rng.randint(0, 4, (8, 1)).astype(np.float32), pos=pos,
            edge_index=radius_graph(pos, 1.5, 8),
            graph_y=rng.rand(1).astype(np.float32)))
    pad = PadSpec.for_batch(5, 8, max(s.num_edges for s in samples))
    batch = collate(samples, pad, [HeadSpec("e", "graph", 1)])
    real = np.asarray(batch.edge_mask) > 0
    ei_real = np.stack([np.asarray(batch.senders)[real],
                        np.asarray(batch.receivers)[real]])
    t = count_triplets(ei_real, batch.x.shape[0])
    batch = add_dimenet_extras(batch, max_triplets=t + 8)
    assert "dn_tri_window" in batch.extras, "span must fit the window here"

    cfg = ModelConfig(
        model_type="DimeNet", input_dim=1, hidden_dim=8, output_dim=(1,),
        output_type=("graph",), graph_head=GraphHeadCfg(1, 8, 1, (8,)),
        node_head=None, task_weights=(1.0,), num_conv_layers=2,
        num_radial=3, num_spherical=4, basis_emb_size=4, int_emb_size=8,
        out_emb_size=8, envelope_exponent=5, num_before_skip=1,
        num_after_skip=1, radius=1.5)
    model = create_model(cfg)
    params = model.init({"params": jax.random.PRNGKey(0)}, batch,
                        train=False)["params"]

    ex_plain = dict(batch.extras)
    del ex_plain["dn_tri_window"]
    batch_plain = batch.replace(extras=ex_plain)

    def loss(p, b):
        out = model.apply({"params": p}, b, train=False)
        return sum(jnp.sum(o ** 2) for o in out)

    lf, gf = jax.value_and_grad(loss)(params, batch)
    lp, gp = jax.value_and_grad(loss)(params, batch_plain)
    assert abs(float(lf) - float(lp)) < 1e-4 * max(1.0, abs(float(lp)))
    for a, c in zip(jax.tree_util.tree_leaves(gf),
                    jax.tree_util.tree_leaves(gp)):
        np.testing.assert_allclose(np.asarray(a), np.asarray(c),
                                   rtol=2e-3, atol=2e-3)
