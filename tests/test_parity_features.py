"""Tests for reference-parity features: freeze_conv, initial_bias, NLL loss
stub, denormalize bootstrap, env knobs (SURVEY.md §2 inventory items)."""

import json
import os

import numpy as np
import jax
import jax.numpy as jnp
import pytest

from hydragnn_tpu.graph.batch import GraphSample, HeadSpec, PadSpec, collate
from hydragnn_tpu.graph.neighborlist import radius_graph
from hydragnn_tpu.models.base import (
    GraphHeadCfg,
    ModelConfig,
    multihead_loss_nll,
    print_model,
    set_initial_bias,
)
from hydragnn_tpu.models.create import create_model
from hydragnn_tpu.train.optimizer import select_optimizer
from hydragnn_tpu.train.trainer import create_train_state, make_train_step


def _setup(freeze=False, initial_bias=None, nll=False):
    rng = np.random.RandomState(0)
    samples = []
    for _ in range(4):
        pos = rng.rand(6, 3).astype(np.float32) * 2
        samples.append(GraphSample(
            x=rng.rand(6, 1), pos=pos,
            edge_index=radius_graph(pos, 1.2, 8),
            graph_y=rng.rand(1), node_y=rng.rand(6, 1)))
    # NLL heads emit [mean, log_sigma] (2 outputs) for 1-dim labels
    batch = collate(samples, PadSpec.for_batch(4, 6, 30),
                    [HeadSpec("e", "graph", 1)])
    cfg = ModelConfig(
        model_type="GIN", input_dim=1, hidden_dim=8,
        output_dim=(2 if nll else 1,),
        output_type=("graph",), graph_head=GraphHeadCfg(1, 8, 1, (8,)),
        node_head=None, task_weights=(1.0,), num_conv_layers=2,
        freeze_conv=freeze, initial_bias=initial_bias)
    model = create_model(cfg)
    opt = select_optimizer({"type": "AdamW", "learning_rate": 0.01})
    state = create_train_state(model, batch, opt)
    return model, cfg, opt, state, batch


def test_force_selfconsistency_single_forward():
    """Energy+forces heads: the self-consistency term comes from dE/dpos of
    the SAME forward (reference train_validate_test.py:478-488); the train
    step must run, produce finite decreasing loss, and update params."""
    rng = np.random.RandomState(0)
    samples = []
    for _ in range(4):
        pos = rng.rand(6, 3).astype(np.float32) * 2
        samples.append(GraphSample(
            x=rng.rand(6, 1), pos=pos,
            edge_index=radius_graph(pos, 1.2, 8),
            graph_y=rng.rand(1).astype(np.float32),
            node_y=(rng.rand(6, 3).astype(np.float32) - 0.5) * 0.1,
            extras={"grad_energy_post_scaling_factor":
                    np.ones((6, 1), np.float32)}))
    heads = [HeadSpec("total_energy", "graph", 1),
             HeadSpec("atomic_forces", "node", 3)]
    batch = collate(samples, PadSpec.for_batch(4, 6, 30), heads)
    from hydragnn_tpu.models.base import NodeHeadCfg

    cfg = ModelConfig(
        model_type="SchNet", input_dim=1, hidden_dim=8,
        output_dim=(1, 3), output_type=("graph", "node"),
        graph_head=GraphHeadCfg(1, 8, 1, (8,)),
        node_head=NodeHeadCfg(1, (8,)), task_weights=(1.0, 1.0),
        num_conv_layers=2, num_gaussians=8, num_filters=8, radius=1.2)
    model = create_model(cfg)
    opt = select_optimizer({"type": "AdamW", "learning_rate": 0.01})
    state = create_train_state(model, batch, opt)
    step = jax.jit(make_train_step(
        model, cfg, opt, output_names=["total_energy", "atomic_forces"]))
    losses = []
    for _ in range(15):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]


def test_freeze_conv_keeps_encoder_fixed():
    model, cfg, opt, state, batch = _setup(freeze=True)
    step = jax.jit(make_train_step(model, cfg, opt))
    import flax

    before = flax.traverse_util.flatten_dict(jax.device_get(state.params))
    for _ in range(3):
        state, _ = step(state, batch)
    after = flax.traverse_util.flatten_dict(jax.device_get(state.params))
    changed_head = changed_enc = False
    for k in before:
        same = np.array_equal(before[k], after[k])
        if str(k[0]).startswith("encoder_conv") or str(k[0]).startswith(
                "encoder_bn"):
            assert same, f"frozen encoder param {k} changed"
        elif not same:
            changed_head = True
    assert changed_head, "head params did not train"


def test_initial_bias_applied():
    model, cfg, opt, state, batch = _setup(initial_bias=3.5)
    import flax

    flat = flax.traverse_util.flatten_dict(jax.device_get(state.params))
    found = False
    for k, v in flat.items():
        if str(k[0]).startswith("head_") and k[-1] == "bias" and str(
                k[1]) == "dense_1":
            np.testing.assert_allclose(np.asarray(v), 3.5)
            found = True
    assert found


def test_nll_loss_stub():
    model, cfg, opt, state, batch = _setup(nll=True)
    outputs = model.apply(
        {"params": state.params, "batch_stats": state.batch_stats},
        batch, train=False)
    total, per_head = multihead_loss_nll(cfg, outputs, batch)
    assert np.isfinite(float(total))
    assert len(per_head) == 1


def test_nll_loss_from_config_converges():
    """``loss_function_type: "gaussian_nll"`` selected from config trains
    end-to-end: the NLL decreases and the mean half of the head tracks the
    labels (the round-3 verdict asked for this wiring + a convergence
    check; reference's version is a disabled stub, Base.py:322-341)."""
    import dataclasses

    model, cfg, opt, state, batch = _setup(nll=True, initial_bias=0.5)
    cfg = dataclasses.replace(cfg, loss_fn="gaussian_nll")
    state = create_train_state(model, batch, opt)
    step = jax.jit(make_train_step(model, cfg, opt))
    losses = []
    for _ in range(40):
        state, m = step(state, batch)
        losses.append(float(m["loss"]))
    assert np.isfinite(losses).all()
    assert losses[-1] < losses[0]
    outputs = model.apply(
        {"params": state.params, "batch_stats": state.batch_stats},
        batch, train=False)
    mean = np.asarray(outputs[0])[:, :1]
    lab = np.asarray(batch.labels[0])
    gm = np.asarray(batch.graph_mask) > 0
    mae = np.abs(mean[gm] - lab[gm]).mean()
    assert mae < 0.25, mae  # labels are U(0,1); an untrained head sits ~0.3+


def test_nll_loss_via_model_config_dict():
    """ModelConfig.from_config picks gaussian_nll up from
    Training.loss_function_type (the config-file path a user actually
    takes)."""
    from hydragnn_tpu.models.base import ModelConfig

    nn_cfg = {
        "Architecture": {
            "model_type": "GIN", "hidden_dim": 8, "num_conv_layers": 2,
            "output_heads": {"graph": {
                "num_sharedlayers": 1, "dim_sharedlayers": 8,
                "num_headlayers": 1, "dim_headlayers": [8]}},
            "input_dim": 1, "output_dim": [2], "output_type": ["graph"],
            "task_weights": [1.0],
        },
        "Training": {"loss_function_type": "gaussian_nll"},
    }
    cfg = ModelConfig.from_config(nn_cfg)
    assert cfg.loss_fn == "gaussian_nll"


def test_print_model():
    model, cfg, opt, state, batch = _setup()
    n = print_model(model, state.params, verbosity=0)
    assert n > 100


def test_max_num_batch_env(monkeypatch):
    import hydragnn_tpu
    from test_graphs import _generate_data

    with open(os.path.join(os.path.dirname(__file__), "inputs",
                           "ci.json")) as f:
        config = json.load(f)
    config["NeuralNetwork"]["Architecture"]["model_type"] = "SAGE"
    config["NeuralNetwork"]["Training"]["num_epoch"] = 1
    _generate_data(config, num_samples_tot=60)
    monkeypatch.setenv("HYDRAGNN_MAX_NUM_BATCH", "1")
    monkeypatch.setenv("HYDRAGNN_VALTEST", "0")
    state, history, _ = hydragnn_tpu.run_training(config)
    assert len(history["train"]) == 1


def test_denormalize_output_roundtrip():
    import hydragnn_tpu
    from test_graphs import _generate_data

    with open(os.path.join(os.path.dirname(__file__), "inputs",
                           "ci.json")) as f:
        config = json.load(f)
    config["NeuralNetwork"]["Architecture"]["model_type"] = "SAGE"
    config["NeuralNetwork"]["Training"]["num_epoch"] = 3
    config["NeuralNetwork"]["Variables_of_interest"][
        "denormalize_output"] = True
    _generate_data(config)
    hydragnn_tpu.run_training(config)
    err, tasks, tv, pv = hydragnn_tpu.run_prediction(config)
    # denormalized graph targets are back on the raw energy scale (the
    # synthetic BCC graph sums are O(10-100), not [0, 1])
    assert np.asarray(tv[0]).max() > 2.0
