"""Parity tests for the fused row-local residual-MLP chain
(ops/row_mlp.py) vs composed jnp math — forward + all grads,
interpret mode on CPU."""

import numpy as np
import jax
import jax.numpy as jnp

from hydragnn_tpu.ops.row_mlp import dimenet_post_mlp

H, D = 24, 16
NB, NA = 1, 2


def _silu(z):
    return z * jax.nn.sigmoid(z)


def _params(seed=0):
    rng = np.random.RandomState(seed)
    wb = []
    dims = [(D, H), None]  # lin_up, no bias
    wb.append(jnp.asarray(rng.randn(D, H) * 0.3, jnp.float32))
    wb.append(None)
    for _ in range(2 * NB + 1 + 2 * NA):
        wb.append(jnp.asarray(rng.randn(H, H) * 0.3, jnp.float32))
        wb.append(jnp.asarray(rng.randn(H) * 0.1, jnp.float32))
    return tuple(wb)


def _composed(tri, x_ji, x_edge, wb):
    ws, bs = list(wb[0::2]), list(wb[1::2])

    def dense(k, v):
        z = v @ ws[k]
        return z + bs[k] if bs[k] is not None else z

    k = 0
    h = x_ji + _silu(dense(k, tri)); k += 1
    for _ in range(NB):
        t = _silu(dense(k, h)); k += 1
        h = h + _silu(dense(k, t)); k += 1
    h = _silu(dense(k, h)) + x_edge; k += 1
    for _ in range(NA):
        t = _silu(dense(k, h)); k += 1
        h = h + _silu(dense(k, t)); k += 1
    return h


def _inputs(seed=1, e=700):
    rng = np.random.RandomState(seed)
    tri = jnp.asarray(rng.randn(e, D), jnp.float32)
    x_ji = jnp.asarray(rng.randn(e, H), jnp.float32)
    x_edge = jnp.asarray(rng.randn(e, H), jnp.float32)
    return tri, x_ji, x_edge


def test_forward_matches_composed():
    wb = _params()
    tri, x_ji, x_edge = _inputs()
    out = dimenet_post_mlp(tri, x_ji, x_edge, NB, NA, *wb)
    ref = _composed(tri, x_ji, x_edge, wb)
    np.testing.assert_allclose(np.asarray(out), np.asarray(ref),
                               rtol=3e-5, atol=3e-5)


def test_gradients_match_composed():
    wb = _params(seed=2)
    tri, x_ji, x_edge = _inputs(seed=3)
    rng = np.random.RandomState(4)
    wmat = jnp.asarray(rng.randn(*x_edge.shape), jnp.float32)

    diff_wb = [w for w in wb if w is not None]

    def rebuild(dwb):
        it = iter(dwb)
        return tuple(None if w is None else next(it) for w in wb)

    def loss_fused(tri_, x_ji_, x_edge_, dwb):
        out = dimenet_post_mlp(tri_, x_ji_, x_edge_, NB, NA,
                               *rebuild(dwb))
        return jnp.sum(out * wmat)

    def loss_ref(tri_, x_ji_, x_edge_, dwb):
        return jnp.sum(_composed(tri_, x_ji_, x_edge_, rebuild(dwb))
                       * wmat)

    gf = jax.grad(loss_fused, argnums=(0, 1, 2, 3))(
        tri, x_ji, x_edge, diff_wb)
    gr = jax.grad(loss_ref, argnums=(0, 1, 2, 3))(
        tri, x_ji, x_edge, diff_wb)
    for name, a, b in zip(("tri", "x_ji", "x_edge"), gf[:3], gr[:3]):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4, err_msg=name)
    for i, (a, b) in enumerate(zip(gf[3], gr[3])):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=5e-4, atol=5e-4,
                                   err_msg=f"wb[{i}]")
