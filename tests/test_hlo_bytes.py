"""Fusion-boundary byte accounting (utils/hlo_bytes.py)."""

import re

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from hydragnn_tpu.utils.hlo_bytes import (
    entry_fusion_boundary_bytes,
    shape_bytes,
)


def test_shape_bytes():
    assert shape_bytes("f32[512,256]{1,0}") == 512 * 256 * 4
    assert shape_bytes("bf16[8]") == 16
    assert shape_bytes("pred[]") == 1
    assert shape_bytes("(f32[4,4]{1,0}, s32[2])") == 64 + 8
    assert shape_bytes("token[]") == 0


def test_simple_program_bytes():
    @jax.jit
    def f(x, w):
        return jnp.tanh(x @ w)

    x = jnp.ones((128, 64), jnp.float32)
    w = jnp.ones((64, 64), jnp.float32)
    txt = f.lower(x, w).compile().as_text()
    total, per = entry_fusion_boundary_bytes(txt)
    # mandatory traffic: read x (32 KB) + w (16 KB), write out (32 KB);
    # intermediate dot->tanh may or may not fuse — allow one extra
    # round-trip of the 32 KB intermediate, but no more
    lo = (128 * 64 + 64 * 64 + 128 * 64) * 4
    assert lo <= total <= lo + 2 * 128 * 64 * 4, (total, per)


def test_counts_reconsumption_once_per_consumer():
    # y is consumed by two separate kernels (selective sums forced apart by
    # different reductions) — whatever the fusion decisions, the parse output
    # must equal the sum over entry instructions of operands+outputs,
    # all of which appear in the per-instruction map
    @jax.jit
    def f(x):
        y = x * 2.0
        return jnp.sum(y, axis=0), jnp.sum(y, axis=1)

    x = jnp.ones((64, 32), jnp.float32)
    txt = f.lower(x).compile().as_text()
    total, per = entry_fusion_boundary_bytes(txt)
    assert total == sum(per.values())
    assert total >= 64 * 32 * 4  # at least reads x once


def test_train_step_bytes_far_below_cost_model():
    """The whole point: fusion-boundary bytes must land well under the
    fusion-blind cost model for a gather/scatter-heavy program."""
    idx = jnp.asarray(np.random.RandomState(0).randint(0, 64, 512), jnp.int32)

    @jax.jit
    def f(nodes, w):
        msg = jnp.tanh(nodes[idx] @ w)
        agg = jax.ops.segment_sum(msg, idx, num_segments=64)
        return jnp.sum(agg**2)

    nodes = jnp.ones((64, 64), jnp.float32)
    w = jnp.ones((64, 64), jnp.float32)
    compiled = f.lower(nodes, w).compile()
    txt = compiled.as_text()
    m = re.search(r"^ENTRY[^{]*\{(.*?)^\}", txt, re.S | re.M)
    if m and not re.search(r"\bfusion\(", m.group(1)):
        # Some backends (CPU XLA lowers segment_sum to a `while` loop
        # carrying the full state tuple) emit an ENTRY with ZERO fusion
        # instructions.  With no fusions, the fusion-boundary walk
        # degenerates to a fusion-blind per-op sum — every intermediate
        # counts as HBM traffic, including the while-carry rewrites —
        # which legitimately EXCEEDS the cost model (~14% here) instead
        # of landing below it.  The estimator's claim ("fusion
        # boundaries are where bytes move") is only testable on a
        # compile that actually fused; skip on evidence from the HLO
        # itself rather than on the backend name.
        pytest.skip("compiled ENTRY has no fusion instructions — "
                    "fusion-boundary accounting is vacuous here")
    total, _ = entry_fusion_boundary_bytes(txt)
    ca = compiled.cost_analysis()
    ca = ca[0] if isinstance(ca, (list, tuple)) else ca
    cm = float(ca.get("bytes accessed", 0.0))
    if cm > 0:
        assert total <= cm * 1.05, (total, cm)


def test_memory_space_and_async_skipped():
    hlo = """HloModule m

ENTRY %main (p: f32[128,64]) -> f32[128,64] {
  %p = f32[128,64]{1,0} parameter(0)
  %vmem = f32[128,64]{1,0:T(8,128)S(1)} fusion(%p), kind=kLoop
  %smem = s32[]{:S(2)} fusion(%p), kind=kLoop
  %start = ((f32[128,64]), f32[32,64]{1,0:T(8,128)S(1)}, s32[]) async-start(%p)
  %done = f32[32,64]{1,0:T(8,128)S(1)} async-done(%start)
  ROOT %out = f32[128,64]{1,0} fusion(%vmem), kind=kLoop
}
"""
    total, per = entry_fusion_boundary_bytes(hlo)
    b = 128 * 64 * 4
    # vmem fusion: reads p (HBM) -> b, writes VMEM -> 0
    # smem fusion: reads p -> b, writes SMEM -> 0
    # async pair: skipped entirely
    # out fusion: reads VMEM (0), writes HBM -> b
    assert per["vmem"] == b
    assert per["smem"] == b
    assert "start" not in per and "done" not in per
    assert per["out"] == b
    assert total == 3 * b
