"""Config-schema checks (parity: reference tests/test_config.py:16-40 checks
required keys; plus finalize() inference unit checks)."""

import json
import os

import numpy as np

from hydragnn_tpu.config.config import (
    DatasetStats,
    finalize,
    get_log_name_config,
    head_specs_from_config,
    label_slices_from_config,
)

_REQUIRED_TOP = ["Verbosity", "Dataset", "NeuralNetwork"]
_REQUIRED_NN = ["Architecture", "Variables_of_interest", "Training"]
_REQUIRED_ARCH = ["model_type", "hidden_dim", "num_conv_layers", "output_heads"]
_REQUIRED_TRAINING = ["num_epoch", "batch_size", "Optimizer", "perc_train"]


def _load(name):
    with open(os.path.join(os.path.dirname(__file__), "inputs", name)) as f:
        return json.load(f)


def test_required_keys_present():
    for fname in ["ci.json", "ci_multihead.json", "ci_equivariant.json",
                  "ci_vectoroutput.json", "ci_conv_head.json"]:
        config = _load(fname)
        for k in _REQUIRED_TOP:
            assert k in config, f"{fname} missing {k}"
        for k in _REQUIRED_NN:
            assert k in config["NeuralNetwork"], f"{fname} missing {k}"
        for k in _REQUIRED_ARCH:
            assert k in config["NeuralNetwork"]["Architecture"]
        for k in _REQUIRED_TRAINING:
            assert k in config["NeuralNetwork"]["Training"]


def test_finalize_inference():
    config = _load("ci_multihead.json")
    config["NeuralNetwork"]["Architecture"]["model_type"] = "SAGE"
    stats = DatasetStats(
        num_nodes_sample=8, graph_size_variable=True, max_nodes=8, max_edges=48)
    out = finalize(config, stats)
    arch = out["NeuralNetwork"]["Architecture"]
    assert arch["output_dim"] == [1, 1, 1, 1]
    assert arch["output_type"] == ["graph", "node", "node", "node"]
    assert arch["input_dim"] == 1
    assert arch["edge_dim"] is None
    # original config untouched (finalize is pure)
    assert "output_dim" not in config["NeuralNetwork"]["Architecture"]


def test_finalize_pna_requires_deg():
    import pytest

    config = _load("ci.json")
    stats = DatasetStats(num_nodes_sample=8, graph_size_variable=True)
    with pytest.raises(AssertionError):
        finalize(config, stats)  # PNA without degree histogram
    stats = DatasetStats(
        num_nodes_sample=8, graph_size_variable=True, pna_deg=[0, 4, 10, 2])
    out = finalize(config, stats)
    assert out["NeuralNetwork"]["Architecture"]["pna_deg"] == [0, 4, 10, 2]
    assert out["NeuralNetwork"]["Architecture"]["max_neighbours"] == 3


def test_edge_features_validation():
    import pytest

    config = _load("ci.json")
    config["NeuralNetwork"]["Architecture"]["model_type"] = "GIN"
    config["NeuralNetwork"]["Architecture"]["edge_features"] = ["lengths"]
    stats = DatasetStats(num_nodes_sample=8, graph_size_variable=True)
    with pytest.raises(AssertionError):
        finalize(config, stats)


def test_label_slices():
    config = _load("ci_vectoroutput.json")
    gs, ns = label_slices_from_config(config)
    # graph dims [1,2,1]; node dims [2,1,2]
    assert gs[1] == (0, 1)   # "sum" -> graph feature 0
    assert gs[2] == (1, 3)   # "sums_vec" -> graph feature 1
    assert gs[3] == (3, 4)   # "sum_linear" -> graph feature 2
    assert ns[0] == (3, 5)   # "x2x3_vec" -> node feature 2
    assert ns[4] == (2, 3)   # "x" -> node feature 1
    assert ns[5] == (0, 2)   # "xx2_vec" -> node feature 0


def test_log_name_and_head_specs():
    config = _load("ci.json")
    stats = DatasetStats(
        num_nodes_sample=8, graph_size_variable=True, pna_deg=[0, 4])
    out = finalize(config, stats)
    name = get_log_name_config(out)
    assert "PNA" in name and "hd-8" in name
    specs = head_specs_from_config(out)
    assert len(specs) == 1 and specs[0].type == "graph" and specs[0].dim == 1
