"""ZeRO sharded training (parallel/zero.py, docs/SCALING.md §4): primitive
shard/consolidate exactness, the in-shard_map slice round trip, stage-1/2
train-step parity with the replicated mesh path on the virtual 8-device CPU
mesh, measured per-device byte savings, the non-elementwise (LAMB) guard,
config/env knob resolution, and trainer-level consolidate-on-save /
re-shard-on-resume bit parity.
"""

import json
import os

import jax
import jax.numpy as jnp
import numpy as np
import optax
import pytest

from hydragnn_tpu.models.create import create_model
from hydragnn_tpu.parallel.mesh import (
    _shard_map,
    make_dp_eval_step,
    make_dp_train_step,
    make_mesh,
    make_multislice_mesh,
    replicate_state,
    stack_batches,
)
from hydragnn_tpu.parallel.zero import (
    ZeroSharding,
    check_zero_stage,
    consolidate_opt_state,
    consolidate_state,
    measured_device_bytes,
    shard_opt_state,
    shard_tree,
    sharding_report,
    unshard_tree,
    unshard_tree_dims,
    zero_shard_state,
    zero_stage_from_training,
)
from hydragnn_tpu.train.optimizer import select_optimizer
from hydragnn_tpu.train.trainer import create_train_state

from jax.sharding import PartitionSpec as P

from tests.test_distributed_mesh import _cfg, _make_batches

N_DEV = 8


def _tree():
    rng = np.random.RandomState(3)
    return {
        "w": rng.randn(13, 5).astype(np.float32),   # non-divisible by 8
        "b": rng.randn(7).astype(np.float32),        # smaller than n
        "count": np.asarray(4, np.int32),            # scalar leaf
        "big": rng.randn(32, 3).astype(np.float32),  # divisible (no pad)
    }


def _leaves_equal(a, b):
    la = jax.tree_util.tree_leaves(jax.device_get(a))
    lb = jax.tree_util.tree_leaves(jax.device_get(b))
    return len(la) == len(lb) and all(
        np.array_equal(np.asarray(x), np.asarray(y)) for x, y in zip(la, lb))


# ---------------------------------------------------------------------------
# primitives
# ---------------------------------------------------------------------------


def test_shard_consolidate_roundtrip_exact():
    """shard -> consolidate is the identity: padding stripped, scalars
    untouched, dtypes preserved, values bit-identical."""
    assert len(jax.devices()) == N_DEV
    mesh = make_mesh()
    tree = _tree()
    sharded, specs, dims = shard_opt_state(tree, mesh, "data")
    # leading dims padded to a multiple of the shard count; scalars intact
    assert sharded["w"].shape == (16, 5) and sharded["b"].shape == (8,)
    assert sharded["big"].shape == (32, 3) and sharded["count"].shape == ()
    assert specs["w"] == P("data") and specs["count"] == P()
    assert dims == {"w": 13, "b": 7, "count": None, "big": 32}
    # every device holds exactly 1/8 of each padded rank>=1 leaf
    rows = {s.data.shape[0] for s in sharded["w"].addressable_shards}
    assert rows == {2}
    back = consolidate_opt_state(sharded, dims, mesh)
    for k in tree:
        got = np.asarray(jax.device_get(back[k]))
        assert got.dtype == tree[k].dtype
        assert np.array_equal(got, tree[k]), k


def test_shard_unshard_identity_inside_shard_map():
    """The in-step slice/gather pair (shard_tree -> unshard_tree /
    unshard_tree_dims) is the identity for divisible, non-divisible and
    scalar leaves alike."""
    mesh = make_mesh()
    tree = {k: v for k, v in _tree().items()}
    dims = jax.tree.map(
        lambda x: None if np.ndim(x) == 0 else int(np.shape(x)[0]), tree)

    def body(t):
        idx = jax.lax.axis_index("data")
        sl = shard_tree(t, idx, N_DEV)
        via_template = unshard_tree(sl, t, "data")
        via_dims = unshard_tree_dims(sl, dims, "data")
        return via_template, via_dims

    f = jax.jit(_shard_map(body, mesh, in_specs=(P(),), out_specs=(P(), P())))
    a, b = f(tree)
    assert _leaves_equal(a, tree)
    assert _leaves_equal(b, tree)


def test_multislice_spec_selection_ici():
    """On a (dcn, ici) multi-slice mesh the partition defaults to the
    innermost (ici) axis so the per-step all_gather stays off DCN."""
    mesh = make_multislice_mesh(jax.devices(), num_slices=2)
    cfg = _cfg()
    model = create_model(cfg)
    opt = select_optimizer({"type": "AdamW", "learning_rate": 1e-3})
    (batch,), _ = _make_batches(1)
    state = create_train_state(model, batch, opt)
    z_state, zs = zero_shard_state(state, mesh, stage=1)
    assert isinstance(zs, ZeroSharding)
    assert zs.axis == "ici" and zs.n == 4 and zs.stage == 1
    leaf = [x for x in jax.tree_util.tree_leaves(z_state.opt_state)
            if np.ndim(x) >= 1][0]
    assert leaf.sharding.spec[0] == "ici"
    back = consolidate_state(z_state, zs, mesh)
    assert _leaves_equal(back.opt_state, jax.device_get(state.opt_state))


def test_sliced_adamw_update_exactly_matches_full():
    """The mathematical heart of the ZeRO claim: ELEMENTWISE optimizers
    partition exactly.  Two sequential AdamW updates computed slice-by-slice
    (sliced grads/params/moments, like the in-step dance) reassemble to the
    BIT-IDENTICAL params and moments of the full-tree updates."""
    rng = np.random.RandomState(0)
    params = {"w": rng.randn(13, 5).astype(np.float32),
              "b": rng.randn(7).astype(np.float32)}
    grads = {"w": rng.randn(13, 5).astype(np.float32),
             "b": rng.randn(7).astype(np.float32)}
    tx = optax.inject_hyperparams(optax.adamw)(learning_rate=0.01)
    n = N_DEV

    def slice_i(tree, i):
        return jax.device_get(jax.tree.map(
            lambda x: shard_tree(jnp.asarray(x), i, n)
            if np.ndim(x) else x, tree))

    st_full = tx.init(params)
    p_full = params
    st_sl = st_full
    p_sl = params
    jit_update = jax.jit(tx.update)  # hoisted: one trace cache (TRC003)
    for _ in range(2):
        u, st_full = jit_update(grads, st_full, p_full)
        p_full = optax.apply_updates(p_full, u)

        outs = []
        for i in range(n):
            u_i, st_i = jit_update(
                slice_i(grads, i), slice_i(st_sl, i), slice_i(p_sl, i))
            outs.append((optax.apply_updates(slice_i(p_sl, i), u_i), st_i))
        # reassemble: concat rank>=1 leaves and unpad; scalars from shard 0
        def gather(trees, template):
            leaves0, treedef = jax.tree_util.tree_flatten(trees[0])
            tmpl = treedef.flatten_up_to(template)
            out = []
            for li, t in enumerate(tmpl):
                parts = [np.asarray(jax.tree_util.tree_leaves(tr)[li])
                         for tr in trees]
                if np.ndim(parts[0]) == 0:
                    out.append(parts[0])
                else:
                    out.append(np.concatenate(parts, 0)[: np.shape(t)[0]])
            return jax.tree_util.tree_unflatten(treedef, out)

        p_sl = gather([jax.device_get(o[0]) for o in outs], p_sl)
        st_sl = gather([jax.device_get(o[1]) for o in outs], st_sl)

    assert _leaves_equal(p_full, p_sl)
    assert _leaves_equal(st_full, st_sl)


# ---------------------------------------------------------------------------
# mesh train-step parity + measured bytes (acceptance assertions)
# ---------------------------------------------------------------------------


def test_zero_step_parity_and_device_bytes():
    """ZeRO-1 and stage-2 train steps on the 8-device mesh track the
    replicated mesh step step-for-step: the FIRST step is bit-identical,
    later steps stay within float tolerance (the residual is cross-program
    XLA fusion jitter, not partitioning error — the same reason the
    existing mesh-vs-single tests use rtol), and measured per-device
    optimizer-state bytes come in under 1/N of replicated plus the padded
    slices."""
    mesh = make_mesh()
    cfg = _cfg()
    model = create_model(cfg)
    opt = select_optimizer({"type": "AdamW", "learning_rate": 0.01})
    batches, _ = _make_batches(N_DEV * 4, seed=3)
    state0 = create_train_state(model, batches[0], opt, seed=0)

    s_rep = replicate_state(state0, mesh)
    step_rep = make_dp_train_step(model, cfg, opt, mesh,
                                  telemetry_metrics=True)
    s_z1, zs1 = zero_shard_state(state0, mesh, stage=1)
    step_z1 = make_dp_train_step(model, cfg, opt, mesh, zero_specs=zs1,
                                 telemetry_metrics=True)
    s_z2, zs2 = zero_shard_state(state0, mesh, stage=2)
    step_z2 = make_dp_train_step(model, cfg, opt, mesh, zero_specs=zs2,
                                 telemetry_metrics=True)

    # -- measured per-device resident bytes (the 1/N claim) -----------------
    rep1 = sharding_report(s_z1, zs1)
    dev0 = mesh.devices.flat[0]
    meas_opt = measured_device_bytes(s_z1.opt_state, dev0)
    assert meas_opt == rep1["opt_bytes_per_device"]  # analytic == measured
    repl_opt = rep1["opt_bytes_replicated"]
    # bound: scalar leaves (step counts, injected lr) stay replicated on
    # every device; everything ELSE must come in at 1/N of replicated plus
    # the padded slice rows
    scalar_opt = sum(
        np.asarray(x).nbytes
        for x in jax.tree_util.tree_leaves(jax.device_get(state0.opt_state))
        if np.ndim(x) == 0)
    assert meas_opt - scalar_opt <= (repl_opt - scalar_opt) / N_DEV + \
        rep1["padded_waste_bytes_per_device"] + 1
    assert rep1["param_bytes_per_device"] == rep1["param_bytes_replicated"]
    rep2 = sharding_report(s_z2, zs2)
    meas_p = measured_device_bytes(s_z2.params, dev0)
    assert meas_p == rep2["param_bytes_per_device"]
    assert meas_p <= rep2["param_bytes_replicated"] / N_DEV + \
        rep2["padded_waste_bytes_per_device"] + 1

    # -- step-for-step parity ----------------------------------------------
    # the ZeRO-1 run is the trajectory; each step the replicated and
    # stage-2 twins RESTART from its consolidated state, so every
    # comparison is one step from bit-identical inputs (two different XLA
    # programs drift chaotically over many Adam steps — eps-division
    # amplifies 1-ulp fusion jitter — which is compile noise, not
    # partitioning error; the sliced-update microtest above proves the
    # dance itself is exact)
    for i in range(3):
        stacked = stack_batches(batches[i * N_DEV:(i + 1) * N_DEV])
        host = jax.device_get(consolidate_state(s_z1, zs1, mesh))
        s_rep = replicate_state(host, mesh)
        s_z2, zs2 = zero_shard_state(host, mesh, stage=2)
        s_rep, m_rep = step_rep(s_rep, stacked)
        s_z1, m_z1 = step_z1(s_z1, stacked)
        s_z2, m_z2 = step_z2(s_z2, stacked)
        np.testing.assert_allclose(float(m_z1["loss"]), float(m_rep["loss"]),
                                   rtol=1e-6)
        np.testing.assert_allclose(float(m_z2["loss"]), float(m_rep["loss"]),
                                   rtol=1e-6)
        # telemetry norms must be STAGE-INDEPENDENT: the sharded psum-of-
        # slice-norms (scalar leaves counted once, outside the psum) has to
        # agree with the replicated full-tree norms
        for key in ("update_norm", "param_norm"):
            np.testing.assert_allclose(float(m_z1[key]), float(m_rep[key]),
                                       rtol=1e-4)
            np.testing.assert_allclose(float(m_z2[key]), float(m_rep[key]),
                                       rtol=1e-4)
        for a, b, c in zip(
                jax.tree_util.tree_leaves(jax.device_get(s_rep.params)),
                jax.tree_util.tree_leaves(jax.device_get(s_z1.params)),
                jax.tree_util.tree_leaves(jax.device_get(
                    consolidate_state(s_z2, zs2, mesh).params))):
            np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                       rtol=1e-5, atol=1e-7)
            np.testing.assert_allclose(np.asarray(c), np.asarray(a),
                                       rtol=1e-5, atol=1e-7)

    # -- eval step under sharded state (specs must match, values agree) -----
    ev_rep = make_dp_eval_step(model, cfg, mesh)
    ev_z2 = make_dp_eval_step(model, cfg, mesh, zero=zs2)
    stacked = stack_batches(batches[:N_DEV])
    m_r = ev_rep(s_rep, stacked)
    m_2 = ev_z2(s_z2, stacked)
    np.testing.assert_allclose(float(m_2["loss"]), float(m_r["loss"]),
                               rtol=1e-4)


def test_zero_scanned_dispatch_matches_sequential_steps():
    """steps>1 (scan-chunked dispatch, HYDRAGNN_STEPS_PER_DISPATCH) composes
    with ZeRO: one scanned 2-step dispatch over sharded state equals two
    sequential sharded steps."""
    mesh = make_mesh()
    cfg = _cfg()
    model = create_model(cfg)
    opt = select_optimizer({"type": "AdamW", "learning_rate": 0.01})
    batches, _ = _make_batches(N_DEV * 2, seed=11)
    state0 = create_train_state(model, batches[0], opt, seed=0)

    s_seq, zs = zero_shard_state(state0, mesh, stage=1)
    step1 = make_dp_train_step(model, cfg, opt, mesh, zero_specs=zs)
    s1 = stack_batches(batches[:N_DEV])
    s2 = stack_batches(batches[N_DEV:])
    s_seq, m1 = step1(s_seq, s1)
    s_seq, m2 = step1(s_seq, s2)

    s_scan, zs_b = zero_shard_state(state0, mesh, stage=1)
    step2 = make_dp_train_step(model, cfg, opt, mesh, zero_specs=zs_b,
                               steps=2)
    super_batch = jax.tree.map(lambda a, b: np.stack([a, b]), s1, s2)
    s_scan, ms = step2(s_scan, super_batch)

    ng = float(m1["num_graphs"]) + float(m2["num_graphs"])
    want = (float(m1["loss"]) * float(m1["num_graphs"])
            + float(m2["loss"]) * float(m2["num_graphs"])) / ng
    np.testing.assert_allclose(float(ms["loss"]), want, rtol=1e-6)
    for a, b in zip(jax.tree_util.tree_leaves(jax.device_get(s_seq.params)),
                    jax.tree_util.tree_leaves(jax.device_get(s_scan.params))):
        np.testing.assert_allclose(np.asarray(b), np.asarray(a),
                                   rtol=1e-5, atol=1e-7)


# ---------------------------------------------------------------------------
# knob resolution + guards
# ---------------------------------------------------------------------------


def test_zero_stage_validation_and_env(monkeypatch):
    assert check_zero_stage("2") == 2
    for bad in (3, -1, "x", None, 1.5):
        with pytest.raises(ValueError):
            check_zero_stage(bad)
    monkeypatch.delenv("HYDRAGNN_ZERO", raising=False)
    assert zero_stage_from_training({}) == 0
    assert zero_stage_from_training({"zero_stage": 2}) == 2
    # legacy reference knob lifts the floor to stage 1
    assert zero_stage_from_training(
        {"Optimizer": {"use_zero_redundancy": True}}) == 1
    assert zero_stage_from_training(
        {"zero_stage": 2, "Optimizer": {"use_zero_redundancy": True}}) == 2
    # env wins over config, in both directions
    monkeypatch.setenv("HYDRAGNN_ZERO", "1")
    assert zero_stage_from_training({"zero_stage": 2}) == 1
    monkeypatch.setenv("HYDRAGNN_ZERO", "0")
    assert zero_stage_from_training(
        {"Optimizer": {"use_zero_redundancy": True}}) == 0
    monkeypatch.setenv("HYDRAGNN_ZERO", "7")
    with pytest.raises(ValueError):
        zero_stage_from_training({})
    # set-but-EMPTY = unset (wrapper scripts exporting HYDRAGNN_ZERO= must
    # not silently force a memory-sized-for-sharding job replicated)
    monkeypatch.setenv("HYDRAGNN_ZERO", "")
    assert zero_stage_from_training({"zero_stage": 2}) == 2
    # env=False = the config-declared stage only: what select_optimizer
    # refuses LAMB for (an env-FORCED stage must instead reach the
    # trainer's warn-and-disable, not raise at run_training startup)
    monkeypatch.setenv("HYDRAGNN_ZERO", "2")
    assert zero_stage_from_training({"zero_stage": 1}, env=False) == 1
    assert zero_stage_from_training({}, env=False) == 0


def test_config_finalize_writes_and_validates_zero_stage():
    from hydragnn_tpu.config.config import DatasetStats, finalize

    def _cfg_dict(**training):
        return {"NeuralNetwork": {
            "Architecture": {"model_type": "SAGE", "hidden_dim": 8,
                             "num_conv_layers": 2, "output_heads": {}},
            "Variables_of_interest": {"type": ["graph"], "output_index": [0],
                                      "output_dim": [1],
                                      "input_node_features": [0]},
            "Training": {"num_epoch": 1, "batch_size": 4, **training},
        }}

    stats = DatasetStats(num_nodes_sample=10, graph_size_variable=False)
    out = finalize(_cfg_dict(), stats)
    assert out["NeuralNetwork"]["Training"]["zero_stage"] == 0
    out = finalize(_cfg_dict(zero_stage="1"), stats)
    assert out["NeuralNetwork"]["Training"]["zero_stage"] == 1
    with pytest.raises(ValueError):
        finalize(_cfg_dict(zero_stage=5), stats)


def test_lamb_zero_guard_raises_at_config_time():
    """The docstring caveat is now enforced: ZeRO + a per-tensor (LAMB)
    optimizer raises in select_optimizer instead of silently changing the
    trust-ratio numerics."""
    for opt_type in ("LAMB", "FusedLAMB"):
        with pytest.raises(ValueError, match="elementwise"):
            select_optimizer({"type": opt_type}, zero_stage=1)
        with pytest.raises(ValueError, match="elementwise"):
            select_optimizer({"type": opt_type, "use_zero_redundancy": True})
        # without ZeRO, LAMB stays available
        spec = select_optimizer({"type": opt_type})
        assert spec.name == opt_type
    # elementwise optimizers pass with any stage
    assert select_optimizer({"type": "AdamW"}, zero_stage=2).name == "AdamW"


# ---------------------------------------------------------------------------
# trainer integration: parity, resume round trip, fallback, telemetry
# ---------------------------------------------------------------------------


def test_trainer_zero1_parity_and_resume_bit_exact(tmp_path, monkeypatch):
    """Acceptance: ZeRO-1 training through the real trainer matches the
    replicated mesh path, and a chaos-preempted ZeRO run resumed from its
    (consolidated) bundle reproduces the uninterrupted ZeRO run BIT-FOR-BIT
    — consolidate-on-save / re-shard-on-load preserves mid-epoch parity."""
    from hydragnn_tpu.resilience import load_resume_bundle, resume_dir
    from tests.test_resilience import _Loaders, _fresh_skeleton, _run

    monkeypatch.delenv("HYDRAGNN_CHAOS_PREEMPT_STEP", raising=False)
    monkeypatch.delenv("HYDRAGNN_ZERO", raising=False)
    loaders = _Loaders(n_train=64, batch_size=4)

    state_rep, hist_rep = _run(loaders, tmp_path, "zrepl", use_mesh_dp=True)
    state_z, hist_z = _run(loaders, tmp_path, "zzero", use_mesh_dp=True,
                           training_extra={"zero_stage": 1})
    # returned state is CONSOLIDATED: same (full, unpadded) leaf shapes as
    # the replicated run's
    assert [np.shape(x) for x in jax.tree_util.tree_leaves(
                jax.device_get(state_z.opt_state))] == \
           [np.shape(x) for x in jax.tree_util.tree_leaves(
                jax.device_get(state_rep.opt_state))]
    np.testing.assert_allclose(hist_z["train"], hist_rep["train"], rtol=1e-5)
    # params: loose tolerance by design — over 18 Adam steps the two
    # DIFFERENT XLA programs amplify 1-ulp fusion jitter through the
    # eps-division (compile noise, present between any two trace variants;
    # the step-level and sliced-update tests pin the partitioning itself
    # to exact/ulp level)
    for a, b in zip(
            jax.tree_util.tree_leaves(jax.device_get(state_rep.params)),
            jax.tree_util.tree_leaves(jax.device_get(state_z.params))):
        np.testing.assert_allclose(np.asarray(a), np.asarray(b),
                                   rtol=0.1, atol=5e-3)
    assert hist_z["pipeline"]["zero_stage"] == 1
    assert hist_rep["pipeline"]["zero_stage"] == 0

    # preempt the ZeRO run mid-epoch 1 and resume: bit parity vs state_z
    monkeypatch.setenv("HYDRAGNN_CHAOS_PREEMPT_STEP", "3")
    _, hist_v = _run(loaders, tmp_path, "zvictim", use_mesh_dp=True,
                     training_extra={"zero_stage": 1})
    assert hist_v.get("preempted") is True
    monkeypatch.delenv("HYDRAGNN_CHAOS_PREEMPT_STEP")
    bundle = load_resume_bundle(_fresh_skeleton(loaders),
                                resume_dir(str(tmp_path), "zvictim"))
    assert bundle is not None
    state_r, meta = bundle
    assert meta["pipeline"]["zero_stage"] == 1
    state_c, hist_c = _run(loaders, tmp_path, "zvictim", use_mesh_dp=True,
                           training_extra={"zero_stage": 1},
                           resume_meta=meta, state=state_r)
    assert "preempted" not in hist_c
    assert _leaves_equal(state_c.params, state_z.params)
    assert _leaves_equal(state_c.opt_state, state_z.opt_state)


def test_trainer_zero2_e2e_with_telemetry_and_teleview(tmp_path, monkeypatch,
                                                      capsys):
    """Stage 2 end-to-end through the trainer: loss drops, the returned
    state is consolidated (full unpadded shapes), the telemetry manifest
    carries the `sharding` block with the per-device byte measurements, and
    teleview renders it."""
    from tests.test_resilience import _Loaders, _run

    monkeypatch.delenv("HYDRAGNN_ZERO", raising=False)
    monkeypatch.setenv("HYDRAGNN_TELEMETRY", "1")
    monkeypatch.setenv("HYDRAGNN_TELEMETRY_SINKS", "jsonl")
    loaders = _Loaders(n_train=64, batch_size=4)
    state, hist = _run(loaders, tmp_path, "zstage2", num_epoch=2,
                       use_mesh_dp=True, training_extra={"zero_stage": 2})
    monkeypatch.delenv("HYDRAGNN_TELEMETRY")
    assert hist["train"][-1] < hist["train"][0]
    assert hist["pipeline"]["zero_stage"] == 2
    # consolidated: every param/opt leaf back at its original (unpadded)
    # shape — a fresh skeleton is the ground truth
    from tests.test_resilience import _fresh_skeleton

    skeleton = _fresh_skeleton(loaders)
    assert [np.shape(x) for x in jax.tree_util.tree_leaves(
                jax.device_get(state.params))] == \
           [np.shape(x) for x in jax.tree_util.tree_leaves(
                jax.device_get(skeleton.params))]
    assert [np.shape(x) for x in jax.tree_util.tree_leaves(
                jax.device_get(state.opt_state))] == \
           [np.shape(x) for x in jax.tree_util.tree_leaves(
                jax.device_get(skeleton.opt_state))]

    events = os.path.join(str(tmp_path), "zstage2", "telemetry",
                          "events.jsonl")
    recs = [json.loads(l) for l in open(events) if l.strip()]
    shard_recs = [r for r in recs if r.get("event") == "sharding"]
    assert shard_recs, "no sharding event emitted"
    s = shard_recs[-1]
    assert s["zero_stage"] == 2 and s["axis_size"] == N_DEV
    assert s["opt_bytes_per_device"] * 2 < s["opt_bytes_replicated"]
    assert s["param_bytes_per_device"] * 2 < s["param_bytes_replicated"]
    manifest = [r for r in recs if r.get("event") == "manifest"][-1]
    assert manifest["sharding"]["zero_stage"] == 2

    import tools.teleview as teleview

    teleview.main([events])
    out = capsys.readouterr().out
    assert "sharding:" in out
    assert "zero_stage=2" in out
    assert "WARNING" not in out.split("sharding:")[1].split("\n\n")[0]


def test_trainer_zero_fallback_paths_warn(tmp_path, monkeypatch):
    """ZeRO requested where it cannot apply falls back LOUDLY to
    replicated: the local-jit path warns, and an env-forced ZeRO over a
    non-elementwise optimizer warns-and-disables instead of changing
    numerics (the config-declared combination already raises in
    select_optimizer)."""
    from tests.test_resilience import _Loaders, _run

    monkeypatch.delenv("HYDRAGNN_ZERO", raising=False)
    loaders = _Loaders(n_train=16, batch_size=8)
    with pytest.warns(UserWarning, match="local-jit path"):
        _, hist = _run(loaders, tmp_path, "zlocal", num_epoch=1,
                       use_mesh_dp=False, training_extra={"zero_stage": 1})
    assert hist["pipeline"]["zero_stage"] == 0

    # env-forced ZeRO over a hand-built LAMB spec: the trainer (not
    # select_optimizer, which never saw the env knob) warns-and-disables
    from hydragnn_tpu.train.trainer import create_train_state, \
        train_validate_test
    from tests.test_resilience import _model

    monkeypatch.setenv("HYDRAGNN_ZERO", "1")
    cfg, model = _model()
    opt = select_optimizer({"type": "FusedLAMB", "learning_rate": 1e-3})
    train_l, val_l, test_l = loaders()
    state = create_train_state(model, next(iter(train_l)), opt)
    with pytest.warns(UserWarning, match="not elementwise"):
        _, hist = train_validate_test(
            model, cfg, state, opt, train_l, val_l, test_l,
            {"Training": {"num_epoch": 1},
             "Variables_of_interest": {"output_names": ["e"]}},
            log_name="zlamb", logs_dir=str(tmp_path), use_mesh_dp=False)
    monkeypatch.delenv("HYDRAGNN_ZERO")
    assert hist["pipeline"]["zero_stage"] == 0
