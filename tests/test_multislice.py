"""Multi-slice (dcn x ici) mesh tests on the virtual 8-device CPU mesh.

A 2x4 multi-slice mesh emulates a 2-slice pod: DP spans both axes (gradient
pmean reduces hierarchically — ICI within a slice, DCN across), and ZeRO-1
shards optimizer state along ici only so its all_gather never crosses DCN.
Numerically every configuration must match the plain 1-axis DP step.
"""

import numpy as np
import jax
import pytest

from hydragnn_tpu.parallel.mesh import (
    ICI_AXIS,
    make_dp_train_step,
    make_mesh,
    make_multislice_mesh,
    mesh_dp_axes,
    replicate_state,
    stack_batches,
)
from hydragnn_tpu.train.optimizer import select_optimizer
from hydragnn_tpu.train.trainer import create_train_state

from tests.test_distributed_mesh import _cfg, _make_batches


def _setup(n_dev=8):
    devices = jax.devices()[:n_dev]
    if len(devices) < n_dev:
        pytest.skip(f"needs {n_dev} devices")
    (batch,), _ = _make_batches(1)
    cfg = _cfg()
    from hydragnn_tpu.models.create import create_model

    model = create_model(cfg)
    opt = select_optimizer({"type": "AdamW", "learning_rate": 1e-3})
    state = create_train_state(model, batch, opt)
    stacked = stack_batches([batch] * n_dev)
    return devices, model, cfg, opt, state, stacked


def _params_close(a, b, tol=1e-5):
    fa = jax.tree_util.tree_leaves(a)
    fb = jax.tree_util.tree_leaves(b)
    for x, y in zip(fa, fb):
        np.testing.assert_allclose(
            np.asarray(x), np.asarray(y), rtol=tol, atol=tol)


def test_multislice_mesh_shape():
    devices = jax.devices()[:8]
    mesh = make_multislice_mesh(devices, num_slices=2)
    assert tuple(mesh.axis_names) == ("dcn", "ici")
    assert mesh.shape["dcn"] == 2 and mesh.shape["ici"] == 4
    assert mesh_dp_axes(mesh) == ("dcn", "ici")
    with pytest.raises(ValueError):
        make_multislice_mesh(devices[:6], num_slices=4)


def test_multislice_step_matches_flat_dp():
    devices, model, cfg, opt, state, stacked = _setup()

    flat = make_mesh(devices)
    s1 = replicate_state(state, flat)
    step1 = make_dp_train_step(model, cfg, opt, flat)
    s1, m1 = step1(s1, stacked)

    ms = make_multislice_mesh(devices, num_slices=2)
    s2 = replicate_state(state, ms)
    step2 = make_dp_train_step(model, cfg, opt, ms, axis=mesh_dp_axes(ms))
    s2, m2 = step2(s2, stacked)

    assert abs(float(m1["loss"]) - float(m2["loss"])) < 1e-6
    _params_close(s1.params, s2.params)


def test_multislice_zero_over_ici_matches():
    """ZeRO-1 sharded along ici on the 2x4 mesh must train identically to
    the unsharded multi-slice step, with opt state split 4 ways (not 8)."""
    from hydragnn_tpu.parallel.zero import shard_state_for_zero

    devices, model, cfg, opt, state, stacked = _setup()
    ms = make_multislice_mesh(devices, num_slices=2)
    axes = mesh_dp_axes(ms)

    base = replicate_state(state, ms)
    base_step = make_dp_train_step(model, cfg, opt, ms, axis=axes)
    base2, mb = base_step(base, stacked)

    z_state, zero_specs, zero_dims = shard_state_for_zero(state, ms)
    z_step = make_dp_train_step(model, cfg, opt, ms, axis=axes,
                                zero_specs=zero_specs)
    z2, mz = z_step(z_state, stacked)

    assert abs(float(mb["loss"]) - float(mz["loss"])) < 1e-6
    _params_close(base2.params, z2.params)

    # opt state leaves are sharded along ici (4 shards), replicated over dcn
    ici = ms.shape[ICI_AXIS]
    leaves = [x for x in jax.tree_util.tree_leaves(z2.opt_state)
              if hasattr(x, "sharding") and np.ndim(x) >= 1]
    assert leaves, "no sharded optimizer-state leaves found"
    for leaf in leaves:
        spec = leaf.sharding.spec
        assert spec and spec[0] == ICI_AXIS, f"leaf not ici-sharded: {spec}"
        shard_rows = {s.data.shape[0] for s in leaf.addressable_shards}
        assert shard_rows == {leaf.shape[0] // ici}


def test_multislice_training_loop_converges():
    """~40 steps over distinct per-device batches on the 2x4 mesh: loss must
    drop, exercising sustained hierarchical gradient reduction."""
    from hydragnn_tpu.models.create import create_model

    n_dev = 8
    devices = jax.devices()[:n_dev]
    if len(devices) < n_dev:
        pytest.skip("needs 8 devices")
    ms = make_multislice_mesh(devices, num_slices=2)
    cfg = _cfg()
    model = create_model(cfg)
    opt = select_optimizer({"type": "AdamW", "learning_rate": 0.01})
    batches, _ = _make_batches(n_dev * 5, seed=3)

    state = replicate_state(
        create_train_state(model, batches[0], opt, seed=0), ms)
    step = make_dp_train_step(model, cfg, opt, ms, axis=mesh_dp_axes(ms))

    losses = []
    for epoch in range(8):
        for i in range(5):
            stacked = stack_batches(batches[i * n_dev:(i + 1) * n_dev])
            state, m = step(state, stacked)
            losses.append(float(m["loss"]))
    assert losses[-1] < losses[0] * 0.5, (losses[0], losses[-1])


def test_multislice_eval_matches_flat():
    from hydragnn_tpu.models.create import create_model
    from hydragnn_tpu.parallel.mesh import make_dp_eval_step

    n_dev = 8
    devices = jax.devices()[:n_dev]
    if len(devices) < n_dev:
        pytest.skip("needs 8 devices")
    cfg = _cfg()
    model = create_model(cfg)
    opt = select_optimizer({"type": "AdamW", "learning_rate": 0.01})
    batches, _ = _make_batches(n_dev, seed=5)
    state = create_train_state(model, batches[0], opt, seed=0)

    flat = make_mesh(devices)
    m1 = make_dp_eval_step(model, cfg, flat)(
        replicate_state(state, flat), stack_batches(batches))

    ms = make_multislice_mesh(devices, num_slices=2)
    m2 = make_dp_eval_step(model, cfg, ms, axis=mesh_dp_axes(ms))(
        replicate_state(state, ms), stack_batches(batches))

    assert np.isclose(float(m1["loss"]), float(m2["loss"]), rtol=1e-5)
