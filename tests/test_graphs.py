"""Integration matrix: all 9 model types × head configs on the deterministic
synthetic BCC task, trained end-to-end through run_training/run_prediction and
checked against the reference's CI accuracy thresholds
(reference tests/test_graphs.py:95-199, thresholds at :126-143)."""

import json
import os

import numpy as np
import pytest

import hydragnn_tpu

# The accuracy matrix trains 26 configs to threshold (~25 min total on the
# CPU mesh; TEST_MATRIX.md).  Until the shard_map import fix these failed
# at import time and cost tier-1 nothing; actually RUNNING them does not
# fit the 870 s tier-1 budget, so they are tier-2 (`-m slow`).
pytestmark = pytest.mark.slow

# RMSE-threshold / sample-MAE-threshold per model (reference
# tests/test_graphs.py:126-136)
THRESHOLDS = {
    "SAGE": [0.20, 0.20],
    "PNA": [0.20, 0.20],
    "MFC": [0.20, 0.20],
    "GIN": [0.25, 0.20],
    "GAT": [0.60, 0.70],
    "CGCNN": [0.50, 0.40],
    "SchNet": [0.20, 0.20],
    "DimeNet": [0.50, 0.50],
    "EGNN": [0.20, 0.20],
}


def _generate_data(config, num_samples_tot=500):
    pt = config["NeuralNetwork"]["Training"]["perc_train"]
    for name, path in config["Dataset"]["path"].items():
        if name == "total":
            n = num_samples_tot
        elif name == "train":
            n = int(num_samples_tot * pt)
        else:
            n = int(num_samples_tot * (1 - pt) * 0.5)
        from ci_data import generate_cached

        generate_cached(name, path, n)


def unittest_train_model(model_type, ci_input, use_lengths=False):
    config_file = os.path.join(
        os.path.dirname(__file__), "inputs", ci_input)
    with open(config_file, "r") as f:
        config = json.load(f)
    config["NeuralNetwork"]["Architecture"]["model_type"] = model_type

    # MFC favors graph-level features in the multihead task; the reference
    # lowers its graph-head weight (reference tests/test_graphs.py:66-67).
    if model_type == "MFC" and ci_input == "ci_multihead.json":
        config["NeuralNetwork"]["Architecture"]["task_weights"][0] = 2

    if use_lengths:
        config["NeuralNetwork"]["Architecture"]["edge_features"] = ["lengths"]

    _generate_data(config)

    hydragnn_tpu.run_training(config)
    error, error_mse_task, true_values, predicted_values = (
        hydragnn_tpu.run_prediction(config))

    thresholds = dict(THRESHOLDS)
    if use_lengths and "vector" not in ci_input:
        thresholds["CGCNN"] = [0.175, 0.175]
        thresholds["PNA"] = [0.10, 0.10]
    if use_lengths and "vector" in ci_input:
        thresholds["PNA"] = [0.2, 0.15]
    if ci_input == "ci_conv_head.json":
        thresholds["GIN"] = [0.25, 0.40]

    for ihead in range(len(true_values)):
        assert error_mse_task[ihead] < thresholds[model_type][0], (
            f"Head RMSE checking failed for head {ihead}: "
            f"{error_mse_task[ihead]} >= {thresholds[model_type][0]}")
        mae = float(np.abs(
            np.asarray(true_values[ihead]) - np.asarray(predicted_values[ihead])
        ).mean())
        assert mae < thresholds[model_type][1], (
            f"MAE sample checking failed for head {ihead}: "
            f"{mae} >= {thresholds[model_type][1]}")

    assert error < thresholds[model_type][0], (
        f"Total RMSE checking failed: {error}")


@pytest.mark.parametrize(
    "model_type",
    ["SAGE", "GIN", "GAT", "MFC", "PNA", "CGCNN", "SchNet", "DimeNet", "EGNN"],
)
@pytest.mark.parametrize("ci_input", ["ci.json", "ci_multihead.json"])
def test_train_model(model_type, ci_input):
    unittest_train_model(model_type, ci_input, False)


@pytest.mark.parametrize("model_type", ["PNA", "CGCNN", "SchNet", "EGNN"])
def test_train_model_lengths(model_type):
    unittest_train_model(model_type, "ci.json", True)


@pytest.mark.parametrize("model_type", ["EGNN", "SchNet"])
def test_train_equivariant_model(model_type):
    unittest_train_model(model_type, "ci_equivariant.json", False)


@pytest.mark.parametrize("model_type", ["PNA"])
def test_train_vector_output(model_type):
    unittest_train_model(model_type, "ci_vectoroutput.json", True)


@pytest.mark.parametrize("model_type", ["GIN"])
def test_train_conv_head(model_type):
    unittest_train_model(model_type, "ci_conv_head.json", False)
