"""Telemetry subsystem: sinks, ring buffer, in-jit norms, padding math,
in-run MFU basis sharing with bench.py, cross-rank reduction, and the
prefetch shm-drain regression.

Tier-1 (not slow-marked): the observability spine every perf PR reports
through has to stay green at the same cadence as the trainer itself.
"""

import json
import os
import re
import socket
import subprocess
import sys
import time

import numpy as np
import pytest
import jax
import jax.numpy as jnp

from hydragnn_tpu.graph.batch import (
    GraphSample,
    HeadSpec,
    PadSpec,
    collate,
)
from hydragnn_tpu.graph.neighborlist import radius_graph
from hydragnn_tpu.models.base import GraphHeadCfg, ModelConfig
from hydragnn_tpu.models.create import create_model
from hydragnn_tpu.train.optimizer import select_optimizer
from hydragnn_tpu.train.trainer import (
    _loss_and_metrics,
    create_train_state,
    make_train_step,
    merge_scanned_metrics,
    tree_l2_norm,
)
from hydragnn_tpu.telemetry import (
    JsonlSink,
    MetricsLogger,
    RingBuffer,
    TelemetryConfig,
    batch_pad_meta,
    waste_pct,
)
from hydragnn_tpu.telemetry.flops import step_cost_flops


def _samples(n_graphs=6, seed=0):
    rng = np.random.RandomState(seed)
    out = []
    for _ in range(n_graphs):
        n = rng.randint(4, 8)
        pos = rng.rand(n, 3).astype(np.float32) * 2.0
        x = rng.randint(0, 4, (n, 1)).astype(np.float32)
        ei = radius_graph(pos, radius=1.2, max_neighbours=8)
        out.append(GraphSample(
            x=x, pos=pos, edge_index=ei,
            graph_y=rng.rand(1).astype(np.float32)))
    return out


def _cfg():
    return ModelConfig(
        model_type="SAGE", input_dim=1, hidden_dim=8, output_dim=(1,),
        output_type=("graph",), graph_head=GraphHeadCfg(2, 8, 1, (8,)),
        node_head=None, task_weights=(1.0,), num_conv_layers=2)


def _batch(samples=None, batch_size=6):
    samples = samples or _samples(batch_size)
    heads = [HeadSpec("energy", "graph", 1)]
    pad = PadSpec.for_batch(batch_size, max(s.num_nodes for s in samples),
                            max(s.num_edges for s in samples))
    return collate(samples, pad, heads), pad, samples


# ---------------------------------------------------------------------------
# sinks + ring buffer
# ---------------------------------------------------------------------------


def test_jsonl_sink_schema_roundtrip(tmp_path):
    path = str(tmp_path / "events.jsonl")
    sink = JsonlSink(path)
    records = [
        {"event": "run_start", "run_id": "r1", "rank": 0, "t": 1.0},
        {"event": "step", "run_id": "r1", "rank": 0, "epoch": 0, "step": 1,
         "loss": 0.5, "tasks": [0.5], "grad_norm": 1.25,
         "step_time_s": 0.01,
         "padding": {"nodes_waste_pct": 12.5, "edges_waste_pct": 25.0}},
        {"event": "epoch", "run_id": "r1", "rank": 0, "epoch": 0,
         "train_loss": 0.5, "val_loss": 0.4, "test_loss": 0.3, "lr": 1e-3,
         "epoch_time_s": 2.0, "train_tasks": [0.5]},
        {"event": "manifest", "run_id": "r1", "total_steps": 1,
         "timers": {"train": {"total_s": 2.0, "count": 1}}},
    ]
    for r in records:
        sink.emit(r)
    sink.close()
    back = [json.loads(line) for line in open(path)]
    assert back == records  # full schema round-trip, key for key
    # numpy scalars must serialize as plain JSON numbers
    sink2 = JsonlSink(path)
    sink2.emit({"event": "step", "loss": np.float32(0.25),
                "num_graphs": np.int64(4)})
    sink2.close()
    last = json.loads(open(path).readlines()[-1])
    assert last["loss"] == 0.25 and last["num_graphs"] == 4


def test_ring_buffer_aggregation():
    ring = RingBuffer(capacity=4)
    for i in range(10):
        ring.push({"loss": float(i), "const": 2.0})
    agg = ring.aggregate()
    # capacity 4: only steps 6..9 remain
    assert agg["loss"]["min"] == 6.0
    assert agg["loss"]["max"] == 9.0
    assert agg["loss"]["avg"] == pytest.approx(7.5)
    assert agg["loss"]["last"] == 9.0
    assert agg["loss"]["count"] == 4
    assert agg["const"]["avg"] == 2.0


# ---------------------------------------------------------------------------
# in-jit metrics
# ---------------------------------------------------------------------------


def test_grad_norm_matches_eager_recompute():
    cfg = _cfg()
    model = create_model(cfg)
    opt = select_optimizer({"type": "AdamW", "learning_rate": 1e-3})
    g, _, _ = _batch()
    state = create_train_state(model, g, opt)
    step = make_train_step(model, cfg, opt, ["energy"],
                           telemetry_metrics=True)
    _, metrics = step(state, g)

    # eager recompute with the SAME dropout fold the step uses
    dropout_rng = jax.random.fold_in(jax.random.PRNGKey(0xD0), state.step)

    def loss_fn(params):
        return _loss_and_metrics(
            model, cfg, params, state.batch_stats, g, True, -1, -1,
            dropout_rng)

    _, grads = jax.value_and_grad(loss_fn, has_aux=True)(state.params)
    want = np.sqrt(sum(
        float(np.sum(np.square(np.asarray(l, np.float64))))
        for l in jax.tree_util.tree_leaves(grads)))
    assert float(metrics["grad_norm"]) == pytest.approx(want, rel=1e-4)
    # param/update norms present and positive
    assert float(metrics["param_norm"]) > 0
    assert float(metrics["update_norm"]) > 0
    # the real-slot counters match the masks
    assert float(metrics["nodes_real"]) == float(np.sum(g.node_mask))
    assert float(metrics["edges_real"]) == float(np.sum(g.edge_mask))


def test_tree_l2_norm_skips_non_float():
    tree = {"a": jnp.asarray([3.0, 4.0]), "n": jnp.asarray([7], jnp.int32)}
    assert float(tree_l2_norm(tree)) == pytest.approx(5.0)


def test_merge_scanned_metrics_counts_vs_means():
    ms = {
        "loss": jnp.asarray([1.0, 3.0]),
        "num_graphs": jnp.asarray([2.0, 6.0]),
        "nodes_real": jnp.asarray([10.0, 20.0]),
        "edges_real": jnp.asarray([4.0, 8.0]),
        "grad_norm": jnp.asarray([1.0, 2.0]),
        "task_0": jnp.asarray([1.0, 3.0]),
    }
    merged = merge_scanned_metrics(ms)
    # counts SUM across the scanned steps
    assert float(merged["num_graphs"]) == 8.0
    assert float(merged["nodes_real"]) == 30.0
    assert float(merged["edges_real"]) == 12.0
    # scalars merge graph-weighted: (1*2 + 3*6) / 8
    assert float(merged["loss"]) == pytest.approx(2.5)
    assert float(merged["task_0"]) == pytest.approx(2.5)
    assert float(merged["grad_norm"]) == pytest.approx((2.0 + 12.0) / 8.0)


# ---------------------------------------------------------------------------
# padding-waste math
# ---------------------------------------------------------------------------


def test_padding_waste_against_hand_built_padspec():
    samples = _samples(4, seed=3)
    heads = [HeadSpec("energy", "graph", 1)]
    pad = PadSpec(num_nodes=64, num_edges=96, num_graphs=5)
    g = collate(samples, pad, heads)
    meta = batch_pad_meta(g)
    assert meta == {"padded_nodes": 64, "padded_edges": 96,
                    "padded_graphs": 5}
    real_nodes = sum(s.num_nodes for s in samples)
    real_edges = sum(s.num_edges for s in samples)
    assert float(np.sum(g.node_mask)) == real_nodes
    assert waste_pct(real_nodes, meta["padded_nodes"]) == pytest.approx(
        (1 - real_nodes / 64) * 100)
    assert waste_pct(real_edges, meta["padded_edges"]) == pytest.approx(
        (1 - real_edges / 96) * 100)
    # stacked batches: leading axes multiply padded slots
    stacked = jax.tree_util.tree_map(
        lambda x: np.stack([np.asarray(x)] * 3), g)
    meta3 = batch_pad_meta(stacked)
    assert meta3 == {"padded_nodes": 3 * 64, "padded_edges": 3 * 96,
                     "padded_graphs": 3 * 5}


# ---------------------------------------------------------------------------
# shared flops basis (bench <-> telemetry)
# ---------------------------------------------------------------------------


def test_bench_uses_shared_flops_helper():
    """bench.py's _cost_flops must be a thin delegate of the telemetry
    helper: same function, same numbers, no drift."""
    sys.path.insert(0, os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))))
    import bench

    def f(a, b):
        return a @ b

    a = jnp.ones((16, 16))
    want = step_cost_flops(f, a, a)
    got = bench._cost_flops(f, a, a)
    assert got == want and want > 0
    # and bench's MFU peak is the telemetry constant
    from hydragnn_tpu.telemetry.flops import MXU_PEAK_FLOPS

    assert bench._mxu_peak() == MXU_PEAK_FLOPS


def test_step_cost_flops_accepts_avals():
    """Lowering from ShapeDtypeStructs (post-donation avals) must work."""
    def f(a, b):
        return a @ b

    aval = jax.ShapeDtypeStruct((8, 8), jnp.float32)
    assert step_cost_flops(f, aval, aval) > 0


# ---------------------------------------------------------------------------
# end-to-end smoke (the ISSUE acceptance criterion) + teleview
# ---------------------------------------------------------------------------


def test_training_smoke_emits_full_jsonl(tmp_path, capsys):
    from hydragnn_tpu.data.dataloader import create_dataloaders
    from hydragnn_tpu.train.trainer import train_validate_test

    samples = _samples(48, seed=1)
    heads = [HeadSpec("energy", "graph", 1)]
    tl, vl, sl = create_dataloaders(
        samples[:32], samples[32:40], samples[40:], 8, heads)
    cfg = _cfg()
    model = create_model(cfg)
    opt = select_optimizer({"type": "AdamW", "learning_rate": 1e-3})
    state = create_train_state(model, next(iter(tl)), opt)
    out_dir = str(tmp_path / "telemetry")
    tele = MetricsLogger(
        TelemetryConfig(enable=True, sinks=("jsonl",)),
        run_name="tele_smoke", out_dir=out_dir)
    state, hist = train_validate_test(
        model, cfg, state, opt, tl, vl, sl,
        {"Training": {"num_epoch": 2},
         "Variables_of_interest": {"output_names": ["energy"]}},
        "tele_smoke", verbosity=0, rank=0, world_size=1,
        use_mesh_dp=False, logs_dir=str(tmp_path), telemetry=tele)

    recs = [json.loads(line)
            for line in open(os.path.join(out_dir, "events.jsonl"))]
    steps = [r for r in recs if r["event"] == "step"]
    epochs = [r for r in recs if r["event"] == "epoch"]
    manifests = [r for r in recs if r["event"] == "manifest"]
    assert len(epochs) == 2 and len(manifests) == 1 and steps
    for r in steps:
        # the acceptance-criterion field set, per step
        assert {"loss", "tasks", "grad_norm", "step_time_s", "padding",
                "run_id", "rank", "epoch", "step"} <= set(r)
        assert "nodes_waste_pct" in r["padding"]
        assert "mfu_est_pct" in r  # CPU cost model supplies flops too
        assert r["tasks"], "per-head losses missing"
    # manifest folds the TimerTracer summaries in
    assert "train" in manifests[-1]["timers"]
    assert manifests[-1]["total_steps"] == steps[-1]["step"]
    # ... and the fused-vs-fallback dispatch tally (trace-time counts of
    # this run's aggregation dispatch decisions; scatter backend here, so
    # every entry is a :scatter fallback)
    disp = manifests[-1]["aggr_dispatch"]
    assert disp and all(k.endswith(":scatter") for k in disp)
    assert manifests[-1]["aggr_dispatch_summary"] == "scatter"
    run_starts = [r for r in recs if r["event"] == "run_start"]
    assert run_starts[-1]["aggr_backend"] == "scatter"
    # epoch record carries loader padding + pipeline accounting
    assert "padding_waste_pct" in epochs[0]

    # tools/teleview.py renders it
    sys.path.insert(0, os.path.join(os.path.dirname(os.path.dirname(
        os.path.abspath(__file__))), "tools"))
    import teleview

    assert teleview.main([out_dir, "--tail", "4"]) == 0
    rendered = capsys.readouterr().out
    assert "mfu%" in rendered and "epochs:" in rendered
    assert "aggr dispatch:" in rendered


def test_disabled_logger_writes_nothing(tmp_path):
    out_dir = str(tmp_path / "telemetry")
    tele = MetricsLogger(TelemetryConfig(enable=False), out_dir=out_dir)
    g, _, _ = _batch()
    tele.begin_epoch(0)
    tele.on_step({"loss": jnp.float32(1.0), "num_graphs": jnp.float32(1.0)},
                 g)
    tele.flush_steps()
    tele.log_epoch(0, {"train_loss": 1.0, "val_loss": 1.0, "test_loss": 1.0,
                       "lr": 1e-3, "epoch_time_s": 1.0, "train_tasks": []})
    tele.finalize()
    assert not os.path.exists(out_dir)


# ---------------------------------------------------------------------------
# cross-rank reduction (2-process harness)
# ---------------------------------------------------------------------------


def _free_port():
    s = socket.socket()
    s.bind(("127.0.0.1", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def test_multi_rank_epoch_reduction(tmp_path):
    worker = os.path.join(os.path.dirname(os.path.abspath(__file__)),
                          "mp_telemetry_worker.py")
    port = _free_port()
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "cpu"
    env.pop("XLA_FLAGS", None)  # one device per process

    procs = [
        subprocess.Popen(
            [sys.executable, worker, str(r), "2", str(port), str(tmp_path)],
            env=env, stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
            text=True)
        for r in range(2)
    ]
    outs = [p.communicate(timeout=300)[0] for p in procs]
    for r, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"rank {r} failed:\n{out[-3000:]}"
    m = re.search(r"TELEMRESULT rank=0 min=([\d.]+) max=([\d.]+) "
                  r"avg=([\d.]+)", outs[0] + outs[1])
    assert m, outs[0][-2000:]
    mn, mx, avg = (float(m.group(i)) for i in (1, 2, 3))
    assert (mn, mx, avg) == (pytest.approx(1.0), pytest.approx(3.0),
                             pytest.approx(2.0))


# ---------------------------------------------------------------------------
# prefetch shm drain regression
# ---------------------------------------------------------------------------


def _shm_entries():
    try:
        return {n for n in os.listdir("/dev/shm") if n.startswith("psm_")}
    except FileNotFoundError:  # non-Linux
        return set()


@pytest.mark.skipif(not os.path.isdir("/dev/shm"),
                    reason="needs /dev/shm to observe segment leaks")
def test_prefetch_shm_drained_on_abandoned_epoch():
    """Abandoning a ProcessPrefetchLoader epoch mid-flight and closing the
    loader must leave ZERO new /dev/shm segments: futures whose cancel()
    fails are blocked on and their segments released (the ADVICE shm-leak
    fix)."""
    from hydragnn_tpu.data.dataloader import GraphDataLoader
    from hydragnn_tpu.data.prefetch import ProcessPrefetchLoader

    samples = _samples(64, seed=5)
    heads = [HeadSpec("energy", "graph", 1)]

    def slow_collate(b):
        time.sleep(0.05)  # keep collations in flight at abandon time
        return b

    loader = GraphDataLoader(samples, heads, 4, shuffle=False,
                             post_collate=slow_collate)
    proc = ProcessPrefetchLoader(loader, num_workers=2, prefetch=4)
    before = _shm_entries()
    try:
        it = iter(proc)
        next(it)
        next(it)
        it.close()  # abandon mid-epoch -> GeneratorExit drain
    finally:
        proc.close()  # settles anything still in flight
    # segments are unlinked synchronously by the drain; allow a short
    # grace for the kernel to reflect it in the directory listing
    for _ in range(50):
        leaked = _shm_entries() - before
        if not leaked:
            break
        time.sleep(0.1)
    assert not leaked, f"leaked shm segments: {sorted(leaked)}"


def test_shm_import_releases_on_failure():
    """_shm_import must unlink the segment even when reconstruction fails
    mid-loop (try/finally regression)."""
    from hydragnn_tpu.data.prefetch import _shm_export, _shm_import

    batch = {"a": np.arange(8, dtype=np.float32)}
    desc = _shm_export(batch)
    tag, name, specs, treedef = desc
    bad = (tag, name, [("a", (8,), "<f4", 0), ("boom",)], treedef)
    with pytest.raises(Exception):
        _shm_import(bad)
    # the segment must be gone despite the failure
    from multiprocessing import shared_memory

    with pytest.raises(FileNotFoundError):
        shared_memory.SharedMemory(name=name)
