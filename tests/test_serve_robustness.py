"""Overload-safe serving (docs/SERVING.md "Overload behavior"):
admission control + load shedding, expired-entry skip before batch
formation, predict watchdog + circuit breaker open/half-open/close,
hot checkpoint reload with golden-batch validation + rollback, and the
/healthz degraded transitions — all driven through the real production
code paths by the serving chaos harness (resilience/chaos.py ServeChaos).
"""

import json
import pickle
import threading
import time
import urllib.error
import urllib.request

import numpy as np
import pytest

from hydragnn_tpu.graph.batch import GraphSample, HeadSpec, PadSpec, collate
from hydragnn_tpu.graph.neighborlist import radius_graph
from hydragnn_tpu.models.base import GraphHeadCfg, ModelConfig
from hydragnn_tpu.models.create import create_model
from hydragnn_tpu.resilience import BreakerOpenError, CircuitBreaker, \
    ServeChaos
from hydragnn_tpu.serve import (
    DeadlineExpiredError,
    InferenceEngine,
    InferenceServer,
    InferenceState,
    MicroBatcher,
    PredictTimeoutError,
    ReloadValidationError,
    RequestShedError,
    ServingConfig,
)


def _sample(n=6, seed=0):
    rng = np.random.RandomState(seed)
    pos = rng.rand(n, 3).astype(np.float32) * 2.0
    return GraphSample(x=rng.rand(n, 1).astype(np.float32), pos=pos,
                       edge_index=radius_graph(pos, 1.2, 8))


_HEADS = [HeadSpec("energy", "graph", 1)]


@pytest.fixture(scope="module")
def engine():
    """One tiny SAGE engine, ONE bucket (single compile) shared by the
    whole module — tier-1 budget discipline."""
    import jax

    cfg = ModelConfig(
        model_type="SAGE", input_dim=1, hidden_dim=8, output_dim=(1,),
        output_type=("graph",), graph_head=GraphHeadCfg(1, 8, 1, (8,)),
        node_head=None, task_weights=(1.0,), num_conv_layers=2)
    model = create_model(cfg)
    pads = [PadSpec.for_batch(4, 16, 64)]
    example = collate([_sample()], pads[0], _HEADS)
    variables = model.init(
        {"params": jax.random.PRNGKey(0), "dropout": jax.random.PRNGKey(1)},
        example, train=False)
    state = InferenceState(step=0, params=variables["params"],
                           batch_stats=variables.get("batch_stats", {}))
    eng = InferenceEngine(cfg, state, _HEADS, pads)
    eng.warmup()
    return eng


def _state_copy(engine, step=1):
    """Host-numpy copy of the engine's live state (a structurally
    identical 'new checkpoint')."""
    import jax

    return InferenceState(
        step=step,
        params=jax.tree_util.tree_map(np.asarray, engine.state.params),
        batch_stats=jax.tree_util.tree_map(np.asarray,
                                           engine.state.batch_stats))


# ---------------------------------------------------------------------------
# Admission control & load shedding
# ---------------------------------------------------------------------------


def test_admission_shed_before_enqueue(engine):
    """A request whose deadline the measured backlog drain already
    exceeds is shed AT SUBMIT (429 path) — it never occupies a queue
    slot, and Retry-After reflects the drain estimate."""
    from hydragnn_tpu.telemetry import MetricsLogger

    b = MicroBatcher(engine, max_wait_ms=0, max_queue=32,
                     telemetry=MetricsLogger.disabled())
    try:
        # prime the drain-rate estimate (20 req/s) without running the
        # worker, then back the queue up: 5 queued / 20 rps = 250 ms
        b._rate_ewma = 20.0
        for i in range(4):
            b.submit(_sample(5, seed=i))  # no deadline: always admitted
        with pytest.raises(RequestShedError) as ei:
            b.submit(_sample(5, seed=9), deadline_s=0.05)
        assert ei.value.retry_after_s >= 0.25
        st = b.stats()
        assert st["shed"] == 1
        assert st["queue_depth"] == 4  # the shed request never queued
        assert b.telemetry.health_counts.get("request_shed") == 1
        # a generous deadline is still admitted through the same path
        b.submit(_sample(5, seed=10), deadline_s=30.0)
        # cold start never sheds: no rate estimate -> no basis
        b2 = MicroBatcher(engine, max_wait_ms=0, max_queue=4)
        b2.submit(_sample(5, seed=11), deadline_s=0.001)
        b2.close(drain=False)
    finally:
        b.close(drain=False)


def test_expired_entries_skipped_at_flush(engine):
    """Entries whose deadline expired in the queue are failed BEFORE
    batch formation; the stale burst does not poison the batch that
    follows it (fresh requests still get real answers)."""
    from hydragnn_tpu.telemetry import MetricsLogger

    b = MicroBatcher(engine, max_wait_ms=0, max_queue=32,
                     telemetry=MetricsLogger.disabled())
    # enqueue BEFORE the worker starts: the tiny deadlines expire while
    # the requests sit in the queue
    dead = [b.submit(_sample(5, seed=i), deadline_s=0.01) for i in range(3)]
    live = [b.submit(_sample(5, seed=10 + i), deadline_s=30.0)
            for i in range(2)]
    time.sleep(0.05)
    b.start()
    try:
        for f in live:
            assert f.result(timeout=30)["energy"].shape == (1,)
        for f in dead:
            with pytest.raises(DeadlineExpiredError):
                f.result(timeout=5)
        st = b.stats()
        assert st["expired"] == 3
        assert b.telemetry.health_counts.get("deadline_expired") == 3
        # not poisoned: a subsequent request is served normally
        assert b.submit(_sample(6, seed=20)).result(
            timeout=30)["energy"].shape == (1,)
    finally:
        b.close()


# ---------------------------------------------------------------------------
# Watchdog + circuit breaker
# ---------------------------------------------------------------------------


def test_breaker_state_machine():
    """Pure state machine: closed -> open at threshold, cooldown ->
    half-open probe, probe failure re-opens, probe success closes;
    threshold 0 disables; transition telemetry lands in the tally."""
    from hydragnn_tpu.telemetry import MetricsLogger

    tel = MetricsLogger.disabled()
    br = CircuitBreaker(threshold=2, cooldown_s=0.08, telemetry=tel)
    assert br.allow() and br.state == "closed"
    br.record_failure()
    assert br.state == "closed" and br.allow()  # below threshold
    br.record_failure()
    assert br.state == "open" and not br.allow()
    assert br.time_to_retry() > 0
    time.sleep(0.1)
    assert br.allow() and br.state == "half_open"  # cooldown elapsed
    br.record_failure()                            # probe fails
    assert br.state == "open"
    time.sleep(0.1)
    assert br.allow() and br.state == "half_open"
    br.record_success()                            # probe succeeds
    assert br.state == "closed" and br.time_to_retry() == 0.0
    # a success resets the consecutive counter
    br.record_failure()
    br.record_success()
    br.record_failure()
    assert br.state == "closed"
    counts = tel.health_counts
    assert counts["breaker_open"] == 2
    assert counts["breaker_half_open"] == 2
    assert counts["breaker_close"] == 1
    # disabled breaker never gates or records
    off = CircuitBreaker(threshold=0)
    off.record_failure()
    assert off.allow() and off.state == "closed"


def test_predict_timeout_watchdog_trips_breaker(engine):
    """Chaos-injected predict latency exceeds the watchdog: the flush
    fails with PredictTimeoutError, consecutive timeouts trip the
    breaker, and further submits fail fast with BreakerOpenError."""
    from hydragnn_tpu.telemetry import MetricsLogger

    tel = MetricsLogger.disabled()
    chaos = ServeChaos(predict_ms=400.0, lat_from=1)
    br = CircuitBreaker(threshold=2, cooldown_s=30.0, telemetry=tel)
    b = MicroBatcher(engine, max_wait_ms=0, max_queue=8, telemetry=tel,
                     predict_timeout_s=0.05, breaker=br,
                     chaos=chaos).start()
    try:
        for seed in (20, 21):
            with pytest.raises(PredictTimeoutError):
                b.submit(_sample(5, seed=seed)).result(timeout=10)
        assert br.state == "open"
        with pytest.raises(BreakerOpenError) as ei:
            b.submit(_sample(5, seed=22))
        assert ei.value.retry_after_s > 0
        st = b.stats()
        assert st["predict_timeouts"] == 2
        assert tel.health_counts.get("predict_timeout") == 2
        assert tel.health_counts.get("breaker_open") == 1
        assert chaos.injected_latency == 2
    finally:
        b.close(drain=False)


def test_breaker_recovery_cycle(engine):
    """Chaos predict failures trip the breaker; after the cooldown the
    next flush is the half-open probe, and its (clean) success closes
    the breaker — the full open -> half-open -> close cycle."""
    from hydragnn_tpu.telemetry import MetricsLogger

    tel = MetricsLogger.disabled()
    chaos = ServeChaos(fail_steps={1, 2})  # first two flushes raise
    br = CircuitBreaker(threshold=2, cooldown_s=0.15, telemetry=tel)
    b = MicroBatcher(engine, max_wait_ms=0, max_queue=8, telemetry=tel,
                     breaker=br, chaos=chaos).start()
    try:
        for seed in (30, 31):
            with pytest.raises(RuntimeError, match="chaos"):
                b.submit(_sample(5, seed=seed)).result(timeout=10)
        assert br.state == "open"
        with pytest.raises(BreakerOpenError):
            b.submit(_sample(5, seed=32))
        time.sleep(0.2)  # cooldown: the next submit becomes the probe
        r = b.submit(_sample(5, seed=33)).result(timeout=10)
        assert r["energy"].shape == (1,)
        assert br.state == "closed"
        assert tel.health_counts.get("breaker_close") == 1
        assert tel.health_counts.get("breaker_half_open", 0) >= 1
    finally:
        b.close()


# ---------------------------------------------------------------------------
# Hot reload: validation, parity, rollback
# ---------------------------------------------------------------------------


def test_reload_parity_and_corrupt_rollback(engine):
    """A structurally-identical checkpoint hot-swaps in with
    bit-identical predictions and zero recompiles; a chaos-corrupted
    candidate fails golden-batch validation, the live state keeps
    serving, and manual rollback restores the pre-reload state."""
    s0 = _sample(7, seed=40)
    r0 = engine.predict_samples([s0])[0]["energy"]
    compiles_before = engine.cache_stats()["compiled_buckets"]

    copy = _state_copy(engine, step=5)
    report = engine.reload_state(copy)
    assert report["step"] == 5
    assert report["golden_max_delta"] == 0.0  # same weights, same outputs
    np.testing.assert_array_equal(
        engine.predict_samples([s0])[0]["energy"], r0)
    # the cached executables are reused across the swap — no recompile
    assert engine.cache_stats()["compiled_buckets"] == compiles_before
    assert engine.telemetry.health_counts.get("reload_ok", 0) >= 1

    # chaos-corrupted candidate: NaN params must fail the golden-batch
    # finiteness check and leave the live state untouched
    chaos = ServeChaos(reload_corrupt=1)
    bad = chaos.on_reload_state(_state_copy(engine, step=6))
    with pytest.raises(ReloadValidationError, match="non-finite"):
        engine.reload_state(bad)
    assert chaos.injected_corruptions == 1
    np.testing.assert_array_equal(
        engine.predict_samples([s0])[0]["energy"], r0)
    assert engine.telemetry.health_counts.get("reload_rollback", 0) >= 1

    # structure mismatch is rejected before any replay
    import jax

    wrong = InferenceState(
        step=7,
        params=jax.tree_util.tree_map(
            lambda a: np.zeros(np.shape(a) + (2,), np.float32),
            copy.params),
        batch_stats=copy.batch_stats)
    with pytest.raises(ReloadValidationError, match="structure"):
        engine.reload_state(wrong)

    # manual rollback restores the retained pre-reload state exactly once
    assert engine.rollback(reason="test") is True
    assert engine.rollback() is False
    np.testing.assert_array_equal(
        engine.predict_samples([s0])[0]["energy"], r0)
    stats = engine.reload_stats()
    assert stats["reloads"] == 1 and stats["rollbacks"] == 1
    assert stats["reload_failures"] == 2


# ---------------------------------------------------------------------------
# HTTP: 429 + Retry-After, /reload, /healthz degradation, reload under load
# ---------------------------------------------------------------------------


def _post(port, path, obj, timeout=30.0):
    req = urllib.request.Request(
        f"http://127.0.0.1:{port}{path}", data=json.dumps(obj).encode(),
        headers={"Content-Type": "application/json"})
    with urllib.request.urlopen(req, timeout=timeout) as r:
        return r.status, json.loads(r.read())


def _get(port, path, timeout=10.0):
    with urllib.request.urlopen(
            f"http://127.0.0.1:{port}{path}", timeout=timeout) as r:
        return json.loads(r.read())


def _sample_json(s, **extra):
    return {"x": s.x.tolist(), "pos": s.pos.tolist(),
            "edge_index": s.edge_index.tolist(), **extra}


@pytest.fixture()
def server(engine):
    from hydragnn_tpu.telemetry import MetricsLogger

    engine.telemetry = MetricsLogger.disabled()
    srv = InferenceServer(
        engine,
        serving=ServingConfig(port=0, max_wait_ms=5,
                              request_deadline_ms=10_000.0,
                              breaker_threshold=2, breaker_cooldown_s=30.0,
                              predict_timeout_s=30.0),
        chaos=None)
    srv.start()
    yield srv
    srv.shutdown()


def test_http_deadline_shed_429_with_retry_after(server, engine):
    # a zero budget expires in the queue -> shed -> 429 + Retry-After
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}/predict",
        data=json.dumps(_sample_json(_sample(5, seed=50),
                                     timeout_ms=0)).encode(),
        headers={"Content-Type": "application/json"})
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 429
    assert int(ei.value.headers["Retry-After"]) >= 1
    # the header spelling works too
    req = urllib.request.Request(
        f"http://127.0.0.1:{server.port}/predict",
        data=json.dumps(_sample_json(_sample(5, seed=51))).encode(),
        headers={"Content-Type": "application/json", "X-Timeout-Ms": "0"})
    with pytest.raises(urllib.error.HTTPError) as ei:
        urllib.request.urlopen(req, timeout=10)
    assert ei.value.code == 429
    # a sane deadline is served normally
    code, out = _post(server.port, "/predict",
                      _sample_json(_sample(5, seed=52), timeout_ms=10_000))
    assert code == 200 and len(out["heads"]["energy"]) == 1
    # negative timeout_ms is a client error, not a silent clamp
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(server.port, "/predict",
              _sample_json(_sample(5, seed=53), timeout_ms=-5))
    assert ei.value.code == 400


def test_http_reload_healthz_and_breaker_rollback(server, engine, tmp_path):
    """The full reload + degradation story over HTTP: /reload swaps a
    checkpoint (200), a corrupt candidate is rejected with 409 while
    serving continues, a breaker trip inside the reload probation rolls
    the engine back automatically, /healthz degrades while the breaker
    is not closed and recovers after a clean probe."""
    s0 = _sample(6, seed=60)
    base_stats = engine.reload_stats()  # module engine: cumulative
    code, base = _post(server.port, "/predict", _sample_json(s0))
    assert code == 200
    assert _get(server.port, "/healthz")["status"] == "ok"

    # write a real checkpoint pickle (the run_training payload format)
    copy = _state_copy(engine, step=9)
    ck = tmp_path / "cand.pk"
    with open(ck, "wb") as f:  # graftlint: disable=ROB002 (test fixture in tmp dir; crash durability irrelevant)
        pickle.dump({"step": 9, "params": copy.params,
                     "batch_stats": copy.batch_stats}, f)
    code, out = _post(server.port, "/reload", {"checkpoint": str(ck)})
    assert code == 200 and out["status"] == "ok" and out["step"] == 9
    # zero dropped/changed answers across the swap
    code, after = _post(server.port, "/predict", _sample_json(s0))
    assert code == 200 and after["heads"] == base["heads"]

    # corrupt candidate -> 409, old state keeps serving
    bad = ServeChaos(reload_corrupt=1).on_reload_state(copy)
    bad_ck = tmp_path / "bad.pk"
    with open(bad_ck, "wb") as f:  # graftlint: disable=ROB002 (test fixture in tmp dir; crash durability irrelevant)
        pickle.dump({"step": 10, "params": bad.params,
                     "batch_stats": bad.batch_stats}, f)
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(server.port, "/reload", {"checkpoint": str(bad_ck)})
    assert ei.value.code == 409
    assert json.loads(ei.value.read())["status"] == "rolled_back"
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(server.port, "/reload", {"checkpoint": str(tmp_path / "no.pk")})
    assert ei.value.code == 404
    # reload_root allowlist: a path outside the configured root is 403
    # (loopback-only default is what let the requests above through)
    server.serving.reload_root = str(tmp_path)
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(server.port, "/reload", {"checkpoint": "/etc/hostname"})
    assert ei.value.code == 403
    server.serving.reload_root = ""
    code, after = _post(server.port, "/predict", _sample_json(s0))
    assert code == 200 and after["heads"] == base["heads"]

    # breaker trip inside the reload probation: auto-rollback to the
    # pre-reload state + half-open breaker -> /healthz "degraded"
    assert server.engine.reload_stats()["can_rollback"]
    for _ in range(server.breaker.threshold):
        server.breaker.record_failure()
    assert server.engine.reload_stats()["rollbacks"] \
        == base_stats["rollbacks"] + 1
    assert server.breaker.state == "half_open"  # reset by the rollback
    h = _get(server.port, "/healthz")
    assert h["status"] == "degraded"
    assert h["breaker"]["state"] == "half_open"
    # the next clean flush is the probe: service recovers, healthz too
    code, after = _post(server.port, "/predict", _sample_json(s0))
    assert code == 200 and after["heads"] == base["heads"]
    h = _get(server.port, "/healthz")
    assert h["status"] == "ok" and h["breaker"]["state"] == "closed"
    m = _get(server.port, "/metrics")
    assert m["reload"]["reloads"] == base_stats["reloads"] + 1
    assert m["reload"]["rollbacks"] == base_stats["rollbacks"] + 1
    assert m["breaker"]["opens"] == 1  # breaker is per-server: fresh

    # manual POST /rollback (the fleet's rolling-abort path for
    # subprocess replicas): nothing retained -> 409; after a fresh
    # reload it restores the pre-reload state -> 200
    with pytest.raises(urllib.error.HTTPError) as ei:
        _post(server.port, "/rollback", {})
    assert ei.value.code == 409
    code, _ = _post(server.port, "/reload", {"checkpoint": str(ck)})
    assert code == 200
    code, out = _post(server.port, "/rollback", {})
    assert code == 200 and out["status"] == "rolled_back"
    code, after = _post(server.port, "/predict", _sample_json(s0))
    assert code == 200 and after["heads"] == base["heads"]


def test_reload_under_load_zero_drops(server, engine, tmp_path):
    """A hot reload while requests are in flight drops nothing: every
    request before, during and after the swap is answered 200, and
    post-reload predictions are bit-identical (same weights)."""
    s0 = _sample(6, seed=70)
    ref = _post(server.port, "/predict", _sample_json(s0))[1]["heads"]
    copy = _state_copy(engine, step=11)
    ck = tmp_path / "swap.pk"
    with open(ck, "wb") as f:  # graftlint: disable=ROB002 (test fixture in tmp dir; crash durability irrelevant)
        pickle.dump({"step": 11, "params": copy.params,
                     "batch_stats": copy.batch_stats}, f)

    results, errors = [], []

    def client():
        for i in range(16):
            try:
                results.append(_post(server.port, "/predict",
                                     _sample_json(s0)))
            except Exception as e:  # noqa: BLE001
                errors.append(repr(e))

    t = threading.Thread(target=client)
    t.start()
    time.sleep(0.05)  # land the reload mid-stream
    code, out = _post(server.port, "/reload", {"checkpoint": str(ck)})
    assert code == 200 and out["status"] == "ok"
    t.join(timeout=60)
    assert not errors, errors
    assert len(results) == 16
    assert all(code == 200 for code, _ in results)
    # bit-identical across the swap (same weights in the new checkpoint)
    assert all(out["heads"] == ref for _, out in results)


# ---------------------------------------------------------------------------
# Config plumbing for the new knobs
# ---------------------------------------------------------------------------


def test_robustness_config_knobs_and_env(monkeypatch):
    d = ServingConfig()
    assert d.request_deadline_ms > 0 and d.breaker_threshold > 0
    with pytest.raises(ValueError):
        ServingConfig(request_deadline_ms=-1)
    with pytest.raises(ValueError):
        ServingConfig(breaker_threshold=-2)
    with pytest.raises(ValueError):
        ServingConfig(predict_timeout_s=-0.5)
    monkeypatch.setenv("HYDRAGNN_SERVE_DEADLINE_MS", "250")
    monkeypatch.setenv("HYDRAGNN_SERVE_BREAKER_THRESHOLD", "3")
    monkeypatch.setenv("HYDRAGNN_SERVE_BREAKER_COOLDOWN_S", "1.5")
    monkeypatch.setenv("HYDRAGNN_SERVE_PREDICT_TIMEOUT_S", "2.5")
    monkeypatch.setenv("HYDRAGNN_SERVE_RELOAD_WATCH", "/tmp/ck.pk")
    monkeypatch.setenv("HYDRAGNN_SERVE_RELOAD_WATCH_S", "0.5")
    cfg = ServingConfig.from_section({"request_deadline_ms": 9000})
    assert cfg.request_deadline_ms == 250.0  # env wins over config
    assert cfg.breaker_threshold == 3
    assert cfg.breaker_cooldown_s == 1.5
    assert cfg.predict_timeout_s == 2.5
    assert cfg.reload_watch_path == "/tmp/ck.pk"
    assert cfg.reload_watch_s == 0.5
    # the finalize-written Serving defaults carry the new knobs
    from hydragnn_tpu.serve import serving_defaults

    for key in ("request_deadline_ms", "predict_timeout_s",
                "breaker_threshold", "breaker_cooldown_s",
                "reload_probation_s", "reload_watch_path",
                "reload_watch_s", "reload_root"):
        assert key in serving_defaults()
    monkeypatch.setenv("HYDRAGNN_SERVE_RELOAD_ROOT", "/ckpts")
    assert ServingConfig.from_section(None).reload_root == "/ckpts"


def test_serve_chaos_env_parsing(monkeypatch):
    assert ServeChaos.from_env() is None  # nothing armed
    monkeypatch.setenv("HYDRAGNN_CHAOS_SERVE_PREDICT_MS", "250@3+")
    monkeypatch.setenv("HYDRAGNN_CHAOS_SERVE_FAIL_STEP", "2,5")
    monkeypatch.setenv("HYDRAGNN_CHAOS_SERVE_RELOAD_CORRUPT", "1")
    c = ServeChaos.from_env()
    assert c.predict_ms == 250.0 and c.lat_from == 3
    assert c.fail_steps == {2, 5} and c.reload_corrupt == 1
    # bare latency spec arms every flush
    monkeypatch.setenv("HYDRAGNN_CHAOS_SERVE_PREDICT_MS", "100")
    monkeypatch.delenv("HYDRAGNN_CHAOS_SERVE_FAIL_STEP")
    monkeypatch.delenv("HYDRAGNN_CHAOS_SERVE_RELOAD_CORRUPT")
    c = ServeChaos.from_env()
    assert c.predict_ms == 100.0 and c.lat_from == 1
