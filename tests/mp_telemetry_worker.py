"""Worker for the telemetry cross-rank reduction test: two jax.distributed
CPU processes each log one epoch record with a rank-dependent epoch time;
rank 0's JSONL must carry the min/max/avg across BOTH ranks (the host
collectives in MetricsLogger._reduce_ranks are entered by every rank)."""

import json
import os
import sys

rank = int(sys.argv[1])
world = int(sys.argv[2])
port = sys.argv[3]
scratch = sys.argv[4]

os.environ["JAX_PLATFORMS"] = "cpu"

import jax

jax.config.update("jax_platforms", "cpu")
jax.distributed.initialize(
    coordinator_address=f"127.0.0.1:{port}",
    num_processes=world,
    process_id=rank,
)
assert jax.process_count() == world

sys.path.insert(0, os.path.dirname(os.path.dirname(os.path.abspath(__file__))))

from hydragnn_tpu.telemetry import MetricsLogger, TelemetryConfig

out_dir = os.path.join(scratch, f"tele_rank{rank}")
logger = MetricsLogger(
    TelemetryConfig(enable=True, sinks=("jsonl",)),
    run_name="mp_telemetry", out_dir=out_dir,
    rank=rank, world_size=world, cross_rank=True)

# rank 0 -> 1.0s, rank 1 -> 3.0s: reduced min/max/avg must be 1/3/2
logger.log_epoch(0, {
    "train_loss": 0.5, "val_loss": 0.4, "test_loss": 0.3,
    "lr": 1e-3, "epoch_time_s": 1.0 + 2.0 * rank, "train_tasks": [],
})
logger.finalize()

if rank == 0:
    recs = [json.loads(line)
            for line in open(os.path.join(out_dir, "events.jsonl"))]
    ep = [r for r in recs if r["event"] == "epoch"][0]
    rk = ep["ranks"]["epoch_time_s"]
    print(f"TELEMRESULT rank=0 min={rk['min']:.4f} max={rk['max']:.4f} "
          f"avg={rk['avg']:.4f}")
else:
    # non-rank-0 has no sinks; reaching here means the collective matched
    print(f"TELEMRESULT rank={rank} ok=1")
