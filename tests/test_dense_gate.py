"""bench.py --dense acceptance bound (docs/PERF.md PR-15): every dense
rung must clear the MFU floor and every mainline fused arch must report
fused dispatch — pure verdict logic pinned here on synthetic evidence,
plus the CLI exit code and teleview's WARNING rendering of the same
bound."""

import json
import os
import subprocess
import sys

sys.path.insert(0, os.path.dirname(os.path.dirname(
    os.path.abspath(__file__))))
import bench  # noqa: E402

_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))


def _good_evidence():
    return {
        "dense": {
            "SchNet-h256-bf16-b512": {"mfu_pct": 8.5,
                                      "graphs_per_sec": 24000.0},
            "SchNet-h512-bf16-b512": {"mfu_pct": 12.0,
                                      "graphs_per_sec": 16000.0},
            "SchNet-h1024-bf16-b2048-tight": {"mfu_pct": 24.0,
                                              "graphs_per_sec": 9000.0},
        },
        "archs": {
            "SchNet": {"graphs_per_sec": 60000, "aggr_backend": "fused"},
            "GAT": {"graphs_per_sec": 50000, "aggr_backend": "fused"},
            "EGNN": {"graphs_per_sec": 40000, "aggr_backend": "fused"},
            "CGCNN": {"graphs_per_sec": 55000, "aggr_backend": "fused"},
            # non-mainline stacks ride the generic kernels — a scatter
            # tally there is NOT a gate failure
            "SAGE": {"graphs_per_sec": 70000, "aggr_backend": "scatter"},
        },
    }


def test_gate_passes_good_evidence():
    ok, failures, table = bench.dense_gate(_good_evidence())
    assert ok and not failures
    assert {r["name"] for r in table if r["kind"] == "arch"} == {
        "SchNet", "GAT", "EGNN", "CGCNN", "SAGE"}


def test_gate_fails_low_mfu_rung():
    ev = _good_evidence()
    ev["dense"]["SchNet-h256-bf16-b512"]["mfu_pct"] = (
        bench._rung_floor("SchNet-h256-bf16-b512") - 0.1)
    ok, failures, _ = bench.dense_gate(ev)
    assert not ok
    assert any("MFU" in f and "h256" in f for f in failures)


def test_gate_per_rung_floors_raised_above_blanket():
    # the wider rungs are held to floors ABOVE the blanket 5%: an h1024
    # rung at 19% MFU (fine under the old blanket bound) now FAILS
    assert bench._rung_floor("SchNet-h1024-bf16-b2048-tight") > \
        bench.DENSE_MFU_FLOOR
    assert bench._rung_floor("SchNet-h512-bf16-b512") > \
        bench.DENSE_MFU_FLOOR
    # unknown rungs fall back to the blanket floor
    assert bench._rung_floor("GAT-h64-bf16-b512") == bench.DENSE_MFU_FLOOR
    ev = _good_evidence()
    ev["dense"]["SchNet-h1024-bf16-b2048-tight"]["mfu_pct"] = 19.0
    ok, failures, table = bench.dense_gate(ev)
    assert not ok
    assert any("h1024" in f and "20" in f for f in failures)
    floors = {r["name"]: r["mfu_floor"] for r in table
              if r["kind"] == "dense"}
    assert floors["SchNet-h1024-bf16-b2048-tight"] == 20.0
    assert floors["SchNet-h512-bf16-b512"] == 10.0
    assert floors["SchNet-h256-bf16-b512"] == 5.0


def test_gate_fails_mainline_arch_off_fused_path():
    for bad in ("scatter", "mixed(fused=3,scatter=1)", "none", None):
        ev = _good_evidence()
        ev["archs"]["EGNN"]["aggr_backend"] = bad
        ok, failures, _ = bench.dense_gate(ev)
        assert not ok, bad
        assert any("EGNN" in f and "fused path" in f for f in failures)


def test_gate_fails_errored_mainline_and_empty_evidence():
    ev = _good_evidence()
    ev["archs"]["GAT"] = {"error": "RESOURCE_EXHAUSTED"}
    ok, failures, _ = bench.dense_gate(ev)
    assert not ok and any("GAT" in f for f in failures)
    ok, failures, _ = bench.dense_gate({})
    assert not ok and any("no dense/archs evidence" in f for f in failures)


def test_dense_cli_exit_codes(tmp_path):
    good = tmp_path / "good.json"
    good.write_text(json.dumps(_good_evidence()))
    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py"), "--dense",
         "--evidence", str(good)],
        capture_output=True, text=True, cwd=_ROOT)
    assert r.returncode == 0, r.stderr
    line = json.loads(r.stdout.strip().splitlines()[-1])
    assert line["dense_gate"] == "PASS"
    # the BENCH JSON records which archs ran the fused path
    assert set(line["fused_archs"]) == {"SchNet", "GAT", "EGNN", "CGCNN"}
    assert line["mfu_floors"] == bench.DENSE_MFU_FLOORS

    ev = _good_evidence()
    ev["archs"]["SchNet"]["aggr_backend"] = "scatter"
    bad = tmp_path / "bad.json"
    bad.write_text(json.dumps(ev))
    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py"), "--dense",
         "--evidence", str(bad)],
        capture_output=True, text=True, cwd=_ROOT)
    assert r.returncode == 1
    line = json.loads(r.stdout.strip().splitlines()[-1])
    assert line["dense_gate"] == "FAIL" and line["failures"]

    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "bench.py"), "--dense",
         "--evidence", str(tmp_path / "missing.json")],
        capture_output=True, text=True, cwd=_ROOT)
    assert r.returncode == 2


def test_teleview_renders_gate_as_warning(tmp_path):
    events = tmp_path / "events.jsonl"
    events.write_text(json.dumps({"event": "epoch", "epoch": 0,
                                  "train_loss": 1.0}) + "\n")
    ev = _good_evidence()
    ev["archs"]["EGNN"]["aggr_backend"] = "scatter"
    bpath = tmp_path / "BENCH_evidence.json"
    bpath.write_text(json.dumps(ev))
    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "teleview.py"),
         str(events), "--bench", str(bpath)],
        capture_output=True, text=True, cwd=_ROOT)
    # teleview NARRATES the bound (exit 0) where bench --dense enforces it
    assert r.returncode == 0, r.stderr
    assert "WARNING" in r.stdout and "EGNN" in r.stdout

    bpath.write_text(json.dumps(_good_evidence()))
    r = subprocess.run(
        [sys.executable, os.path.join(_ROOT, "tools", "teleview.py"),
         str(events), "--bench", str(bpath)],
        capture_output=True, text=True, cwd=_ROOT)
    assert r.returncode == 0
    assert "PASS every bound held" in r.stdout
