"""Parity tests for the fused EGCL interaction block (ops/egcl_mp.py):
forward, all gradients, masked edges / empty segments, the coordinate
branch on and off, and the model-level EGNN wiring vs the composed path —
interpret mode on CPU."""

import os

import numpy as np
import jax
import jax.numpy as jnp

from hydragnn_tpu.graph.batch import GraphSample, HeadSpec, PadSpec, collate
from hydragnn_tpu.graph.neighborlist import radius_graph
from hydragnn_tpu.ops.egcl_mp import egcl_block

F, H = 16, 24  # distinct feature/hidden widths catch f/h transpositions


def _batch(n_graphs=6, nodes=9, seed=0, isolate=False):
    rng = np.random.RandomState(seed)
    samples = []
    for i in range(n_graphs):
        pos = rng.rand(nodes, 3).astype(np.float32) * 2.2
        if isolate and i == 0:
            # empty segments: park two nodes far outside every cutoff so
            # they have NO incident edges (their agg/psum rows must read 0)
            pos[-2:] += 50.0
        samples.append(GraphSample(
            x=rng.rand(nodes, 2).astype(np.float32), pos=pos,
            edge_index=radius_graph(pos, 1.4, 8),
            graph_y=rng.rand(1).astype(np.float32)))
    pad = PadSpec.for_batch(n_graphs, nodes,
                            max(s.num_edges for s in samples))
    prev = os.environ.get("HYDRAGNN_AGGR_BACKEND")
    os.environ["HYDRAGNN_AGGR_BACKEND"] = "fused"
    try:
        return collate(samples, pad, [HeadSpec("e", "graph", 1)])
    finally:
        if prev is None:
            os.environ.pop("HYDRAGNN_AGGR_BACKEND", None)
        else:
            os.environ["HYDRAGNN_AGGR_BACKEND"] = prev


def _inputs(g, seed=1, edge_attr_dim=0):
    """Random op inputs; geo is [diff(3), radial(1), edge_attr(A)] with
    |diff| < 1 like the real normalized difference."""
    rng = np.random.RandomState(seed)
    n = g.x.shape[0]
    e = g.senders.shape[0]
    x = jnp.asarray(rng.randn(n, F), jnp.float32)
    gd = 4 + edge_attr_dim
    geo = jnp.asarray(rng.rand(e, gd) * 0.8, jnp.float32)
    w0 = jnp.asarray(rng.randn(2 * F + 1 + edge_attr_dim, H) * 0.3,
                     jnp.float32)
    b0 = jnp.asarray(rng.randn(H) * 0.1, jnp.float32)
    w1 = jnp.asarray(rng.randn(H, H) * 0.3, jnp.float32)
    b1 = jnp.asarray(rng.randn(H) * 0.1, jnp.float32)
    wc0 = jnp.asarray(rng.randn(H, H) * 0.3, jnp.float32)
    bc0 = jnp.asarray(rng.randn(H) * 0.1, jnp.float32)
    wc1 = jnp.asarray(rng.randn(H, 1) * 0.5, jnp.float32)
    return x, geo, w0, b0, w1, b1, wc0, bc0, wc1


def _composed(x, geo, mask, w0, b0, w1, b1, wc0, bc0, wc1,
              senders, receivers, n, equivariant):
    """The composed-path math (models/egnn.py fallback route), on raw
    weights."""
    diff, feat = geo[:, :3], geo[:, 3:]
    m = jnp.concatenate([x[senders], x[receivers], feat], axis=-1)
    m = jax.nn.relu(m @ w0 + b0)
    m = jax.nn.relu(m @ w1 + b1)
    m = m * mask[:, None]
    agg = jax.ops.segment_sum(m, senders, num_segments=n)
    if not equivariant:
        return agg, None
    c = jax.nn.relu(m @ wc0 + bc0)
    c = jnp.tanh(c @ wc1)
    trans = jnp.clip(diff * c, -100.0, 100.0) * mask[:, None]
    psum = jax.ops.segment_sum(trans, senders, num_segments=n)
    return agg, psum


def _run_fused(g, args, equivariant):
    x, geo = args[0], args[1]
    em = jnp.asarray(g.edge_mask).astype(jnp.int32)
    perm = jnp.asarray(g.extras["edge_perm_sender"])
    if equivariant:
        return egcl_block(True, x, geo, em, *args[2:],
                          g.senders, g.receivers, perm)
    return egcl_block(False, x, geo, em, *args[2:6], None, None, None,
                      g.senders, g.receivers, perm)


def test_forward_matches_composed():
    g = _batch()
    args = _inputs(g)
    mask = jnp.asarray(g.edge_mask)
    agg, psum = _run_fused(g, args, True)
    ref_agg, ref_psum = _composed(args[0], args[1], mask, *args[2:],
                                  g.senders, g.receivers, args[0].shape[0],
                                  True)
    np.testing.assert_allclose(np.asarray(agg), np.asarray(ref_agg),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(psum[:, :3]),
                               np.asarray(ref_psum), rtol=1e-5, atol=1e-5)


def test_forward_non_equivariant():
    """Last-layer EGCL: no coordinate branch, message sum only."""
    g = _batch(seed=2)
    args = _inputs(g, seed=3)
    mask = jnp.asarray(g.edge_mask)
    agg, psum = _run_fused(g, args, False)
    assert psum is None
    ref_agg, _ = _composed(args[0], args[1], mask, *args[2:],
                           g.senders, g.receivers, args[0].shape[0], False)
    np.testing.assert_allclose(np.asarray(agg), np.asarray(ref_agg),
                               rtol=1e-5, atol=1e-5)


def test_forward_empty_segments():
    """Nodes with no incident edges (isolated + padding slots) read
    exactly zero in both outputs."""
    g = _batch(seed=4, isolate=True)
    args = _inputs(g, seed=5)
    mask = jnp.asarray(g.edge_mask)
    agg, psum = _run_fused(g, args, True)
    ref_agg, ref_psum = _composed(args[0], args[1], mask, *args[2:],
                                  g.senders, g.receivers, args[0].shape[0],
                                  True)
    np.testing.assert_allclose(np.asarray(agg), np.asarray(ref_agg),
                               rtol=1e-5, atol=1e-5)
    np.testing.assert_allclose(np.asarray(psum[:, :3]),
                               np.asarray(ref_psum), rtol=1e-5, atol=1e-5)
    # the isolated nodes really have no edges (the scenario is live)
    deg = np.zeros(args[0].shape[0])
    np.add.at(deg, np.asarray(g.senders)[np.asarray(mask) > 0], 1.0)
    assert (deg == 0).any()
    assert np.all(np.asarray(agg)[deg == 0] == 0.0)


def _grad_parity(g, seed, equivariant, edge_attr_dim=0,
                 rtol=3e-4, atol=3e-4):
    args = _inputs(g, seed=seed, edge_attr_dim=edge_attr_dim)
    mask = jnp.asarray(g.edge_mask)
    n = args[0].shape[0]
    rng = np.random.RandomState(seed + 70)
    wa = jnp.asarray(rng.randn(n, H), jnp.float32)
    wp = jnp.asarray(rng.randn(n, 3), jnp.float32)
    nargs = len(args) if equivariant else 7

    def loss_fused(a):
        agg, psum = _run_fused(g, a, equivariant)
        out = jnp.sum(agg * wa)
        if equivariant:
            out = out + jnp.sum(psum[:, :3] * wp)
        return out

    def loss_ref(a):
        full = tuple(a) + tuple(args[len(a):])
        agg, psum = _composed(full[0], full[1], mask, *full[2:],
                              g.senders, g.receivers, n, equivariant)
        out = jnp.sum(agg * wa)
        if equivariant:
            out = out + jnp.sum(psum * wp)
        return out

    gf = jax.grad(loss_fused)(args[:nargs])
    gr = jax.grad(loss_ref)(args[:nargs])
    emask = np.asarray(g.edge_mask)
    names = ("x", "geo", "w0", "b0", "w1", "b1", "wc0", "bc0", "wc1")
    for name, a, b in zip(names, gf, gr):
        a, b = np.asarray(a), np.asarray(b)
        if name == "geo":
            # contract: masked edges get EXACTLY zero dgeo (their blocks
            # are schedule-skipped; uninitialized rows are where-selected)
            assert np.all(a[emask == 0] == 0.0)
            a, b = a[emask == 1], b[emask == 1]
        np.testing.assert_allclose(a, b, rtol=rtol, atol=atol,
                                   err_msg=name)


def test_gradients_match_composed():
    _grad_parity(_batch(seed=3), seed=6, equivariant=True)


def test_gradients_non_equivariant():
    _grad_parity(_batch(seed=7), seed=8, equivariant=False)


def test_gradients_with_edge_attr():
    """edge_attr lanes ride the geo stream; their grads must chain too."""
    _grad_parity(_batch(seed=9), seed=10, equivariant=True,
                 edge_attr_dim=5)


def test_model_level_fused_equals_composed(monkeypatch):
    """EGNN with the fused block forced on vs off: same params (the
    _DenseParams tree matches the composed path's), same forward, same
    param grads — through BOTH the message and coordinate branches (two
    conv layers: the first is equivariant, so updated positions feed the
    second layer's geometry)."""
    from hydragnn_tpu.models.base import GraphHeadCfg, ModelConfig
    from hydragnn_tpu.models.create import create_model

    g = _batch(n_graphs=4, seed=5)  # fewer edge blocks: interpret mode
    cfg = ModelConfig(
        model_type="EGNN", input_dim=2, hidden_dim=F, output_dim=(1,),
        output_type=("graph",), graph_head=GraphHeadCfg(1, 8, 1, (8,)),
        node_head=None, task_weights=(1.0,), num_conv_layers=2,
        equivariance=True, radius=1.4, max_neighbours=8)
    model = create_model(cfg)
    monkeypatch.setenv("HYDRAGNN_EGCL_FUSED", "1")
    variables = model.init({"params": jax.random.PRNGKey(0)}, g,
                           train=False)

    def loss(params, fused):
        monkeypatch.setenv("HYDRAGNN_EGCL_FUSED", "1" if fused else "0")
        out = model.apply({"params": params}, g, train=False)
        return sum(jnp.sum(o * o) for o in out)

    lf = loss(variables["params"], True)
    lg = loss(variables["params"], False)
    np.testing.assert_allclose(float(lf), float(lg), rtol=2e-5)

    gf = jax.grad(lambda p: loss(p, True))(variables["params"])
    gp = jax.grad(lambda p: loss(p, False))(variables["params"])
    flat_f = jax.tree_util.tree_leaves_with_path(gf)
    flat_p = dict(jax.tree_util.tree_leaves_with_path(gp))
    assert flat_f  # same tree structure both ways
    for path, leaf in flat_f:
        np.testing.assert_allclose(
            np.asarray(leaf), np.asarray(flat_p[path]), rtol=5e-4,
            atol=5e-4, err_msg=str(path))


def test_pipeline_gate_defaults(monkeypatch):
    from hydragnn_tpu.models.egnn import _egcl_pipeline_enabled

    # judge the defaults with the env override ABSENT — a developer's
    # ambient HYDRAGNN_EGCL_FUSED would flip the first assert
    monkeypatch.delenv("HYDRAGNN_EGCL_FUSED", raising=False)
    assert _egcl_pipeline_enabled(64, 64, 4)     # mainline: default ON
    assert not _egcl_pipeline_enabled(256, 64, 4)   # features > tile
    assert not _egcl_pipeline_enabled(64, 256, 4)   # hidden > tile
    assert not _egcl_pipeline_enabled(64, 64, 200)  # geo payload > lanes
    monkeypatch.setenv("HYDRAGNN_EGCL_FUSED", "0")
    assert not _egcl_pipeline_enabled(64, 64, 4)    # forced off
    monkeypatch.setenv("HYDRAGNN_EGCL_FUSED", "1")
    assert _egcl_pipeline_enabled(128, 128, 4)      # forced on


def test_dispatch_tally_counts_egcl(monkeypatch):
    """The egcl dispatch site tallies fused vs scatter — that tally is
    what makes EGNN visible to bench's per-arch aggr_backend column."""
    from hydragnn_tpu.telemetry import pipeline as tp
    from hydragnn_tpu.models.base import GraphHeadCfg, ModelConfig
    from hydragnn_tpu.models.create import create_model

    g = _batch(seed=11)
    cfg = ModelConfig(
        model_type="EGNN", input_dim=2, hidden_dim=F, output_dim=(1,),
        output_type=("graph",), graph_head=GraphHeadCfg(1, 8, 1, (8,)),
        node_head=None, task_weights=(1.0,), num_conv_layers=2,
        equivariance=True, radius=1.4, max_neighbours=8)
    model = create_model(cfg)
    monkeypatch.setenv("HYDRAGNN_EGCL_FUSED", "1")
    before = tp.dispatch_snapshot()
    variables = model.init({"params": jax.random.PRNGKey(0)}, g,
                           train=False)
    model.apply({"params": variables["params"]}, g, train=False)
    delta = tp.dispatch_delta(before, tp.dispatch_snapshot())
    assert delta.get("egcl:fused", 0) > 0
    monkeypatch.setenv("HYDRAGNN_EGCL_FUSED", "0")
    before = tp.dispatch_snapshot()
    model.apply({"params": variables["params"]}, g, train=False)
    delta = tp.dispatch_delta(before, tp.dispatch_snapshot())
    assert delta.get("egcl:scatter", 0) > 0
    # forcing fused requested-but-denied records the fallback reason
    tp.pop_fallbacks("egcl")
    monkeypatch.setenv("HYDRAGNN_EGCL_FUSED", "1")
    monkeypatch.setattr("hydragnn_tpu.ops.egcl_mp.EGCL_H_LIMIT", 1)
    model.apply({"params": variables["params"]}, g, train=False)
    fbs = tp.pop_fallbacks("egcl")
    assert fbs and fbs[0]["reason"] == "width_gate"


def test_bf16_forward_within_tolerance():
    """bf16 node features ride bf16 windows in VMEM; result must stay
    within bf16 tolerance of the f32 composed path."""
    g = _batch(seed=6)
    args = _inputs(g, seed=12)
    mask = jnp.asarray(g.edge_mask)
    bf_args = (args[0].astype(jnp.bfloat16),) + args[1:]
    agg, psum = _run_fused(g, bf_args, True)
    assert agg.dtype == jnp.bfloat16
    ref_agg, ref_psum = _composed(args[0], args[1], mask, *args[2:],
                                  g.senders, g.receivers, args[0].shape[0],
                                  True)
    for out, ref in ((agg, ref_agg), (psum[:, :3], ref_psum)):
        ref = np.asarray(ref, np.float32)
        scale = np.abs(ref).max() + 1e-6
        err = np.abs(np.asarray(out, np.float32) - ref).max() / scale
        assert err < 0.03, err


def test_bf16_gradients_within_tolerance():
    """bf16 operands through the fused backward (weight grads included)
    stay within bf16 drift of the f32 composed reference."""
    g = _batch(seed=13)
    args = _inputs(g, seed=14)
    mask = jnp.asarray(g.edge_mask)
    n = args[0].shape[0]
    rng = np.random.RandomState(15)
    wa = jnp.asarray(rng.randn(n, H), jnp.float32)

    def loss_fused(a):
        bf = (a[0].astype(jnp.bfloat16),) + tuple(a[1:])
        agg, psum = _run_fused(g, bf, True)
        return (jnp.sum(agg.astype(jnp.float32) * wa)
                + jnp.sum(psum[:, :3]))

    def loss_ref(a):
        agg, psum = _composed(a[0], a[1], mask, *a[2:],
                              g.senders, g.receivers, n, True)
        return jnp.sum(agg * wa) + jnp.sum(psum)

    gf = jax.grad(loss_fused)(args)
    gr = jax.grad(loss_ref)(args)
    emask = np.asarray(g.edge_mask).astype(bool)
    names = ("x", "geo", "w0", "b0", "w1", "b1", "wc0", "bc0", "wc1")
    for name, a, b in zip(names, gf, gr):
        a = np.asarray(a, np.float32)
        b = np.asarray(b, np.float32)
        if name == "geo":
            a, b = a[emask], b[emask]
        scale = np.abs(b).max() + 1e-6
        err = np.abs(a - b).max() / scale
        # deeper chain than scf's two matmuls (edge MLP + coord gate +
        # tanh, 4 bf16 matmul layers each way) — drift bound scales with
        # depth; observed ~0.067 max on x grads.  geo's diff lanes carry
        # the gate value c itself (ddiff = c * dpsum), whose relative
        # error is the whole chain's accumulated drift: widest bound.
        assert err < (0.20 if name == "geo" else 0.10), (name, err)
