import os

# Tests run on a virtual 8-device CPU mesh (the reference's analog is its
# dual single-process / mpirun -n 2 CI; see SURVEY.md §4).
#
# XLA_FLAGS must be set before the CPU client is created; jax_platforms is
# forced via config.update because the environment may pre-register a TPU
# plugin at interpreter startup (sitecustomize), which locks JAX_PLATFORMS
# before test code runs.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

# Keep the axon plugin from dialing the TPU tunnel — for THIS process and,
# via env inheritance, for every subprocess the tests spawn (JAX_PLATFORMS
# alone does not stop the dial, and only one process may hold the tunnel:
# a concurrent TPU job would deadlock any test subprocess that dials).
os.environ["PALLAS_AXON_POOL_IPS"] = ""

import sys

_REPO_ROOT = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
if _REPO_ROOT not in sys.path:
    sys.path.insert(0, _REPO_ROOT)

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")

import pytest  # noqa: E402


@pytest.fixture(scope="session", autouse=True)
def _scratch_cwd(tmp_path_factory):
    """Run the whole session from a scratch dir so dataset/, logs/ and
    serialized_dataset/ artifacts never land in the repo.  Dataset files are
    cached across test runs in /tmp to keep reruns fast."""
    scratch = os.environ.get("HYDRAGNN_TEST_SCRATCH", "/tmp/hydragnn_tpu_tests")
    os.makedirs(scratch, exist_ok=True)
    old = os.getcwd()
    os.chdir(scratch)
    os.environ["SERIALIZED_DATA_PATH"] = scratch
    yield scratch
    os.chdir(old)


def pytest_configure(config):
    config.addinivalue_line(
        "markers",
        "slow: heavyweight end-to-end suites (full example/accuracy "
        "training runs) excluded from the tier-1 `-m 'not slow'` pass")
