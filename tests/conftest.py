import os

# Tests run on a virtual 8-device CPU mesh (the reference's analog is its
# dual single-process / mpirun -n 2 CI; see SURVEY.md §4).
#
# XLA_FLAGS must be set before the CPU client is created; jax_platforms is
# forced via config.update because the environment may pre-register a TPU
# plugin at interpreter startup (sitecustomize), which locks JAX_PLATFORMS
# before test code runs.
flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in flags:
    os.environ["XLA_FLAGS"] = (
        flags + " --xla_force_host_platform_device_count=8"
    ).strip()

import jax  # noqa: E402

jax.config.update("jax_platforms", "cpu")
