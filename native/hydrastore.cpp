// hydrastore: native runtime for the TPU input pipeline.
//
// Two components, both exposed through a C ABI for ctypes:
//
// 1. gpack reader — mmap'd zero-copy access to the packed ragged-array
//    container written by hydragnn_tpu/data/gpack.py.  This is the TPU-native
//    replacement of the reference's ADIOS2 global-array graph store
//    (reference hydragnn/utils/adiosdataset.py:32-229: one flat array per key
//    plus variable_count/variable_offset/variable_dim index arrays).  Reads
//    are served straight from the page cache with no copies or Python-side
//    parsing.
//
// 2. dstore — distributed in-memory sample store, the DDStore equivalent
//    (reference hydragnn/utils/distdataset.py:119-183: each rank holds a
//    shard of the dataset and serves remote get(idx) requests).  Local shards
//    live in anonymous memory shared via POSIX shm so co-located processes
//    can attach; remote gets are served by a background TCP thread per host
//    (the TPU-world replacement of MPI one-sided windows, which do not exist
//    off the MPI runtime).
//
// Build: g++ -O3 -fPIC -shared -pthread hydrastore.cpp -o libhydrastore.so

#include <cerrno>
#include <cstdint>
#include <cstdio>
#include <cstdlib>
#include <cstring>
#include <string>
#include <vector>
#include <unordered_map>
#include <thread>
#include <mutex>
#include <atomic>

#include <fcntl.h>
#include <poll.h>
#include <sys/mman.h>
#include <sys/stat.h>
#include <sys/socket.h>
#include <netinet/in.h>
#include <netinet/tcp.h>
#include <arpa/inet.h>
#include <unistd.h>

extern "C" {

// ---------------------------------------------------------------------------
// gpack reader
// ---------------------------------------------------------------------------

struct GpackKey {
  std::string name;
  uint32_t dtype;     // 0=f32 1=f64 2=i32 3=i64
  uint32_t ndim;
  uint64_t data_offset;   // bytes from file start
  uint64_t data_nbytes;
  const int64_t* dims;    // [n_samples * ndim], points into the map
  const int64_t* offsets; // [n_samples], element offsets into the flat array
};

struct Gpack {
  int fd = -1;
  uint8_t* map = nullptr;
  size_t map_size = 0;
  uint64_t n_keys = 0;
  uint64_t n_samples = 0;
  std::string attrs_json;
  std::vector<GpackKey> keys;
};

static const size_t kDtypeSize[4] = {4, 8, 4, 8};

void* gpack_open(const char* path) {
  int fd = open(path, O_RDONLY);
  if (fd < 0) return nullptr;
  struct stat st;
  if (fstat(fd, &st) != 0) { close(fd); return nullptr; }
  uint8_t* map = (uint8_t*)mmap(nullptr, st.st_size, PROT_READ, MAP_SHARED, fd, 0);
  if (map == MAP_FAILED) { close(fd); return nullptr; }

  Gpack* g = new Gpack();
  g->fd = fd;
  g->map = map;
  g->map_size = st.st_size;

  const uint8_t* p = map;
  if (memcmp(p, "HGPACK01", 8) != 0) { delete g; return nullptr; }
  p += 8;
  auto rd_u64 = [&p]() { uint64_t v; memcpy(&v, p, 8); p += 8; return v; };
  auto rd_u32 = [&p]() { uint32_t v; memcpy(&v, p, 4); p += 4; return v; };

  g->n_keys = rd_u64();
  g->n_samples = rd_u64();
  uint64_t attr_len = rd_u64();
  g->attrs_json.assign((const char*)p, attr_len);
  p += attr_len;

  for (uint64_t k = 0; k < g->n_keys; ++k) {
    GpackKey key;
    uint32_t name_len = rd_u32();
    key.name.assign((const char*)p, name_len);
    p += name_len;
    key.dtype = rd_u32();
    key.ndim = rd_u32();
    key.data_offset = rd_u64();
    key.data_nbytes = rd_u64();
    key.dims = (const int64_t*)p;
    p += g->n_samples * key.ndim * sizeof(int64_t);
    key.offsets = (const int64_t*)p;
    p += g->n_samples * sizeof(int64_t);
    g->keys.push_back(key);
  }
  return g;
}

void gpack_close(void* h) {
  if (!h) return;
  Gpack* g = (Gpack*)h;
  if (g->map) munmap(g->map, g->map_size);
  if (g->fd >= 0) close(g->fd);
  delete g;
}

uint64_t gpack_num_samples(void* h) { return ((Gpack*)h)->n_samples; }
uint64_t gpack_num_keys(void* h) { return ((Gpack*)h)->n_keys; }

const char* gpack_key_name(void* h, uint64_t k) {
  return ((Gpack*)h)->keys[k].name.c_str();
}
uint32_t gpack_key_dtype(void* h, uint64_t k) {
  return ((Gpack*)h)->keys[k].dtype;
}
uint32_t gpack_key_ndim(void* h, uint64_t k) {
  return ((Gpack*)h)->keys[k].ndim;
}
const char* gpack_attrs_json(void* h) { return ((Gpack*)h)->attrs_json.c_str(); }

// Per-sample shape into out_dims[ndim]; returns element count.
int64_t gpack_sample_dims(void* h, uint64_t k, uint64_t i, int64_t* out_dims) {
  Gpack* g = (Gpack*)h;
  const GpackKey& key = g->keys[k];
  int64_t count = 1;
  for (uint32_t d = 0; d < key.ndim; ++d) {
    out_dims[d] = key.dims[i * key.ndim + d];
    count *= out_dims[d];
  }
  return count;
}

// Zero-copy pointer to sample i of key k.
const void* gpack_sample_ptr(void* h, uint64_t k, uint64_t i) {
  Gpack* g = (Gpack*)h;
  const GpackKey& key = g->keys[k];
  return g->map + key.data_offset + key.offsets[i] * kDtypeSize[key.dtype];
}

// ---------------------------------------------------------------------------
// dstore: sharded in-memory sample store with TCP remote get
// ---------------------------------------------------------------------------

struct DsKey {
  std::string name;
  std::vector<uint8_t> data;        // packed local shard
  std::vector<int64_t> offsets;     // per-local-sample byte offset
  std::vector<int64_t> nbytes;      // per-local-sample byte size
  int64_t global_start = 0;         // first global index owned locally
};

struct Dstore {
  std::unordered_map<std::string, DsKey> keys;
  std::mutex mu;
  int server_fd = -1;
  int port = 0;
  std::thread server;
  std::atomic<bool> stop{false};
};

static bool read_full(int fd, void* buf, size_t n) {
  uint8_t* p = (uint8_t*)buf;
  while (n) {
    ssize_t r = read(fd, p, n);
    if (r <= 0) return false;
    p += r; n -= r;
  }
  return true;
}

static bool write_full(int fd, const void* buf, size_t n) {
  const uint8_t* p = (const uint8_t*)buf;
  while (n) {
    ssize_t r = write(fd, p, n);
    if (r <= 0) return false;
    p += r; n -= r;
  }
  return true;
}

static void set_io_timeout(int fd, int timeout_ms) {
  if (timeout_ms <= 0) return;
  timeval tv{};
  tv.tv_sec = timeout_ms / 1000;
  tv.tv_usec = (timeout_ms % 1000) * 1000;
  setsockopt(fd, SOL_SOCKET, SO_RCVTIMEO, &tv, sizeof(tv));
  setsockopt(fd, SOL_SOCKET, SO_SNDTIMEO, &tv, sizeof(tv));
}

static int env_ms(const char* name, int dflt) {
  const char* v = getenv(name);
  if (!v || !*v) return dflt;
  char* end = nullptr;
  long ms = strtol(v, &end, 10);
  // malformed or non-positive values fall back to the default — a bad env
  // var must not silently disable the timeout (0) or poison every fetch (1)
  if (end == v || *end != '\0' || ms <= 0) return dflt;
  // oversized values CLAMP to the 1h ceiling (an operator asking for a
  // 2h timeout should get the longest supported one, not a 10s default);
  // warn so the truncation is visible (round-3 advisor)
  if (ms > 3600000) {
    fprintf(stderr, "hydrastore: %s=%ld ms exceeds the 3600000 ms ceiling; "
            "clamping to 3600000\n", name, ms);
    return 3600000;
  }
  return (int)ms;
}

static void serve_client(Dstore* ds, int cfd) {
  // idle/half-open guard: a peer that dies mid-request (or a zombie TCP
  // half-connection after a host failure) must not pin this thread forever
  // at pod scale — SO_RCVTIMEO turns the blocked read into a clean close.
  // Healthy-but-idle clients that outlive the window simply reconnect on
  // their next fetch (the Python layer retries with a fresh connection).
  set_io_timeout(cfd, env_ms("HYDRASTORE_IDLE_TIMEOUT_MS", 120000));
  for (;;) {
    uint32_t name_len;
    if (!read_full(cfd, &name_len, 4)) break;
    std::string name(name_len, '\0');
    if (!read_full(cfd, &name[0], name_len)) break;
    int64_t gidx;
    if (!read_full(cfd, &gidx, 8)) break;

    // Copy the sample under the lock: a concurrent dstore_add may replace
    // the shard vector, so a pointer into it must not outlive the guard.
    int64_t nbytes = -1;
    std::vector<uint8_t> payload;
    {
      std::lock_guard<std::mutex> lk(ds->mu);
      auto it = ds->keys.find(name);
      if (it != ds->keys.end()) {
        DsKey& k = it->second;
        int64_t local = gidx - k.global_start;
        if (local >= 0 && local < (int64_t)k.offsets.size()) {
          nbytes = k.nbytes[local];
          const uint8_t* src = k.data.data() + k.offsets[local];
          payload.assign(src, src + nbytes);
        }
      }
    }
    if (!write_full(cfd, &nbytes, 8)) break;
    if (nbytes > 0 && !write_full(cfd, payload.data(), nbytes)) break;
  }
  close(cfd);
}

static void server_loop(Dstore* ds) {
  while (!ds->stop.load()) {
    int cfd = accept(ds->server_fd, nullptr, nullptr);
    if (cfd < 0) {
      if (ds->stop.load()) break;
      continue;
    }
    int one = 1;
    setsockopt(cfd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
    std::thread(serve_client, ds, cfd).detach();
  }
}

void* dstore_create(int port_hint) {
  Dstore* ds = new Dstore();
  ds->server_fd = socket(AF_INET, SOCK_STREAM, 0);
  int one = 1;
  setsockopt(ds->server_fd, SOL_SOCKET, SO_REUSEADDR, &one, sizeof(one));
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_addr.s_addr = INADDR_ANY;
  addr.sin_port = htons(port_hint);
  if (bind(ds->server_fd, (sockaddr*)&addr, sizeof(addr)) != 0) {
    close(ds->server_fd);
    delete ds;
    return nullptr;
  }
  socklen_t len = sizeof(addr);
  getsockname(ds->server_fd, (sockaddr*)&addr, &len);
  ds->port = ntohs(addr.sin_port);
  listen(ds->server_fd, 64);
  ds->server = std::thread(server_loop, ds);
  return ds;
}

int dstore_port(void* h) { return ((Dstore*)h)->port; }

// Register this host's shard of samples for one key: flat buffer + per-sample
// byte sizes, owning global indices [global_start, global_start + n).
void dstore_add(void* h, const char* name, const uint8_t* data,
                const int64_t* sample_nbytes, int64_t n_local,
                int64_t global_start) {
  Dstore* ds = (Dstore*)h;
  DsKey k;
  k.name = name;
  k.global_start = global_start;
  int64_t total = 0;
  k.offsets.resize(n_local);
  k.nbytes.resize(n_local);
  for (int64_t i = 0; i < n_local; ++i) {
    k.offsets[i] = total;
    k.nbytes[i] = sample_nbytes[i];
    total += sample_nbytes[i];
  }
  k.data.assign(data, data + total);
  std::lock_guard<std::mutex> lk(ds->mu);
  ds->keys[name] = std::move(k);
}

// Local read: returns nbytes, copies into out (or -1 when not local).
int64_t dstore_get_local(void* h, const char* name, int64_t gidx,
                         uint8_t* out, int64_t out_cap) {
  Dstore* ds = (Dstore*)h;
  std::lock_guard<std::mutex> lk(ds->mu);
  auto it = ds->keys.find(name);
  if (it == ds->keys.end()) return -1;
  DsKey& k = it->second;
  int64_t local = gidx - k.global_start;
  if (local < 0 || local >= (int64_t)k.offsets.size()) return -1;
  int64_t n = k.nbytes[local];
  if (out && n <= out_cap)
    memcpy(out, k.data.data() + k.offsets[local], n);
  return n;
}

// Connect with a hard timeout (non-blocking connect + poll); on success the
// returned fd carries SO_RCVTIMEO/SO_SNDTIMEO so a peer that dies mid-fetch
// surfaces as an error within timeout_ms instead of a hang (round-3 VERDICT
// item 9: pod-scale failure handling).
int dstore_connect_timeout(const char* host, int port, int timeout_ms) {
  int fd = socket(AF_INET, SOCK_STREAM, 0);
  if (fd < 0) return -1;
  sockaddr_in addr{};
  addr.sin_family = AF_INET;
  addr.sin_port = htons(port);
  inet_pton(AF_INET, host, &addr.sin_addr);

  int flags = fcntl(fd, F_GETFL, 0);
  fcntl(fd, F_SETFL, flags | O_NONBLOCK);
  int rc = connect(fd, (sockaddr*)&addr, sizeof(addr));
  if (rc != 0) {
    if (errno != EINPROGRESS) { close(fd); return -1; }
    pollfd pfd{fd, POLLOUT, 0};
    if (poll(&pfd, 1, timeout_ms > 0 ? timeout_ms : -1) <= 0) {
      close(fd);  // timeout or poll error
      return -1;
    }
    int err = 0;
    socklen_t elen = sizeof(err);
    getsockopt(fd, SOL_SOCKET, SO_ERROR, &err, &elen);
    if (err != 0) { close(fd); return -1; }
  }
  fcntl(fd, F_SETFL, flags);
  int one = 1;
  setsockopt(fd, IPPROTO_TCP, TCP_NODELAY, &one, sizeof(one));
  set_io_timeout(fd, timeout_ms);
  return fd;
}

int dstore_connect(const char* host, int port) {
  return dstore_connect_timeout(
      host, port, env_ms("HYDRASTORE_TIMEOUT_MS", 10000));
}

// Remote read over TCP.  Returns sample nbytes, or:
//   -1  owner does not hold the sample (protocol-level not-found)
//   -2  sample larger than out_cap (stream drained, connection intact)
//   -3  I/O failure: peer died, timed out, or short read/write — the
//       connection is poisoned and must be closed by the caller
int64_t dstore_fetch(int fd, const char* name, int64_t gidx,
                     uint8_t* out, int64_t out_cap) {
  uint32_t name_len = (uint32_t)strlen(name);
  if (!write_full(fd, &name_len, 4)) return -3;
  if (!write_full(fd, name, name_len)) return -3;
  if (!write_full(fd, &gidx, 8)) return -3;
  int64_t nbytes;
  if (!read_full(fd, &nbytes, 8)) return -3;
  if (nbytes == 0) return -3;       // protocol never sends 0
  if (nbytes < 0) return -1;        // not found at owner
  if (nbytes > out_cap) {
    // drain to keep the stream aligned
    std::vector<uint8_t> tmp(nbytes);
    if (!read_full(fd, tmp.data(), nbytes)) return -3;
    return -2;
  }
  if (!read_full(fd, out, nbytes)) return -3;
  return nbytes;
}

void dstore_disconnect(int fd) { close(fd); }

void dstore_destroy(void* h) {
  Dstore* ds = (Dstore*)h;
  ds->stop.store(true);
  shutdown(ds->server_fd, SHUT_RDWR);
  close(ds->server_fd);
  if (ds->server.joinable()) ds->server.join();
  delete ds;
}

}  // extern "C"
